"""Tests for the scripts/analyze static-analysis suite.

Every rule gets at least one true-positive fixture and one
false-positive-guard fixture; the repo-invariant passes (THRD/JAXP/DTRM)
additionally prove they catch seeded violations the OLD monolithic lint.py
(whose rule set survives as the hygiene/exports/catalogues passes) sailed
past.  The baseline contract is pinned both in-unit and against the real
tree: baseline.json entries must match current findings exactly — no new
findings, no stale pins.
"""

from __future__ import annotations

import ast
import json
import pathlib
import subprocess
import sys
import threading

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from scripts.analyze import catalogues, determinism, excp, exports, hygiene, jitpure, locks, shapes  # noqa: E402
from scripts.analyze.baseline import compare, load_baseline  # noqa: E402
from scripts.analyze.core import DEFAULT_PATHS, Context, SourceFile, load_files  # noqa: E402
from scripts.analyze.driver import PASSES, all_codes, changed_paths, file_scoped_codes, run_passes  # noqa: E402

LEGACY_PASSES = (hygiene, exports, catalogues)
# Exactly the monolithic lint.py's rule codes (ANLZ/THRD/JAXP/DTRM are new).
LEGACY_RULES = {"E999", "W291", "W191", "E711", "E712", "B006", "F841", "F401", "F822", "DEAD", "METR", "SIMC"}


def make_ctx(*files: tuple[str, str], readme: str = "") -> Context:
    out = []
    for rel, code in files:
        try:
            tree = ast.parse(code)
        except SyntaxError:
            tree = None
        out.append(SourceFile(path=pathlib.Path(rel), rel=rel, text=code, lines=code.splitlines(), tree=tree))
    return Context(files=out, root=ROOT, readme=readme)


def rule_hits(findings, rule):
    return [f for f in findings if f.rule == rule]


def legacy_findings(ctx):
    out = []
    for p in LEGACY_PASSES:
        out.extend(f for f in p.run(ctx) if f.rule in LEGACY_RULES)
    return out


# -- hygiene rules: true positive + guard each ------------------------------


def test_e999_syntax_error_and_guard():
    ctx = make_ctx(("tpu_scheduler/bad.py", "def f(:\n"))
    assert rule_hits(run_passes(ctx), "E999")
    ctx = make_ctx(("tpu_scheduler/ok.py", "def f():\n    return 1\n"))
    assert not rule_hits(run_passes(ctx), "E999")


def test_w291_trailing_whitespace_and_guard():
    ctx = make_ctx(("m.py", "x = 1 \n"))
    assert rule_hits(hygiene.run(ctx), "W291")
    ctx = make_ctx(("m.py", "x = 1\n"))
    assert not rule_hits(hygiene.run(ctx), "W291")


def test_w191_tab_indentation_and_guard():
    ctx = make_ctx(("m.py", "if True:\n\tpass\n"))
    assert rule_hits(hygiene.run(ctx), "W191")
    ctx = make_ctx(("m.py", "if True:\n    pass\n"))
    assert not rule_hits(hygiene.run(ctx), "W191")


def test_e711_none_comparison_and_guard():
    ctx = make_ctx(("m.py", "def f(a):\n    return a == None\n"))
    assert rule_hits(hygiene.run(ctx), "E711")
    ctx = make_ctx(("m.py", "def f(a):\n    return a is None\n"))
    assert not rule_hits(hygiene.run(ctx), "E711")


def test_e712_bool_comparison_and_guard():
    ctx = make_ctx(("m.py", "def f(a):\n    return True == a\n"))  # Yoda side too
    assert rule_hits(hygiene.run(ctx), "E712")
    ctx = make_ctx(("m.py", "def f(a):\n    return bool(a)\n"))
    assert not rule_hits(hygiene.run(ctx), "E712")


def test_b006_mutable_default_and_guard():
    ctx = make_ctx(("m.py", "def f(x=[]):\n    return x\n"))
    assert rule_hits(hygiene.run(ctx), "B006")
    ctx = make_ctx(("m.py", "def f(x=()):\n    return x\n"))
    assert not rule_hits(hygiene.run(ctx), "B006")


def test_f841_unused_local_and_guard():
    ctx = make_ctx(("m.py", "def f():\n    unused = 1\n    return 2\n"))
    assert rule_hits(hygiene.run(ctx), "F841")
    # Augmented assignment is a use (ledger pattern), not a dead store.
    ctx = make_ctx(("m.py", "def f(xs):\n    total = 0\n    for x in xs:\n        total += x\n    return total\n"))
    assert not rule_hits(hygiene.run(ctx), "F841")


def test_f401_unused_import_and_guard():
    ctx = make_ctx(("m.py", "import json\nimport os\n\n\ndef f():\n    return os.getpid()\n"))
    hits = rule_hits(hygiene.run(ctx), "F401")
    assert len(hits) == 1 and "'json'" in hits[0].message
    # __init__.py re-exports are exempt.
    ctx = make_ctx(("tpu_scheduler/x/__init__.py", "import json\n"))
    assert not rule_hits(hygiene.run(ctx), "F401")


def test_f822_phantom_export_and_guard():
    ctx = make_ctx(("m.py", '__all__ = ["ghost"]\n'))
    assert rule_hits(hygiene.run(ctx), "F822")
    ctx = make_ctx(("m.py", '__all__ = ["real"]\n\n\ndef real():\n    return 1\n'))
    assert not rule_hits(hygiene.run(ctx), "F822")


def test_e722_bare_except_and_guard():
    ctx = make_ctx(("m.py", "try:\n    x = 1\nexcept:\n    pass\n"))
    assert rule_hits(hygiene.run(ctx), "E722")
    ctx = make_ctx(("m.py", "try:\n    x = 1\nexcept ValueError:\n    pass\n"))
    assert not rule_hits(hygiene.run(ctx), "E722")


def test_e741_ambiguous_name_and_guard():
    ctx = make_ctx(("m.py", "def f(items):\n    l = len(items)\n    return l\n"))
    hits = rule_hits(hygiene.run(ctx), "E741")
    assert len(hits) == 1 and "'l'" in hits[0].message
    # argument form too
    ctx = make_ctx(("m.py", "def f(I):\n    return I\n"))
    assert rule_hits(hygiene.run(ctx), "E741")
    ctx = make_ctx(("m.py", "def f(items):\n    line = len(items)\n    return line\n"))
    assert not rule_hits(hygiene.run(ctx), "E741")


def test_hygiene_covers_tests_and_scripts_trees():
    """The E-/W-/F-series run over the WHOLE analyzed tree — a violation
    seeded under tests/ or scripts/ must be flagged exactly like one in the
    package (this is the coverage guarantee the hygiene docstring pins)."""
    for rel in ("tests/test_seeded.py", "scripts/seeded.py", "tpu_scheduler/seeded.py"):
        ctx = make_ctx((rel, "import json\n\n\ndef f(x=[]):\n    unused = x == None\n    return x \n"))
        found = {f.rule for f in hygiene.run(ctx)}
        assert {"F401", "B006", "E711", "W291"} <= found, (rel, found)


# -- DEAD -------------------------------------------------------------------


def test_dead_export_and_guard():
    mod = ("tpu_scheduler/widgets.py", '__all__ = ["widget"]\n\n\ndef widget():\n    return 1\n')
    ctx = make_ctx(mod)
    assert rule_hits(exports.run(ctx), "DEAD")
    ctx = make_ctx(mod, ("tests/test_widgets.py", "from tpu_scheduler.widgets import widget\n\nprint(widget())\n"))
    assert not rule_hits(exports.run(ctx), "DEAD")


# -- catalogue drift gates --------------------------------------------------


def test_metr_drift_and_guard():
    mod = ("tpu_scheduler/m.py", 'NAME = "scheduler_phantom_total"\n')
    assert rule_hits(catalogues.run(make_ctx(mod, readme="")), "METR")
    assert not rule_hits(catalogues.run(make_ctx(mod, readme="... scheduler_phantom_total ...")), "METR")


def test_simc_drift_and_guard():
    mod = (
        "tpu_scheduler/sim/scenarios.py",
        'def _register(s):\n    return s\n\n\n_register(Scenario(name="ghost-scenario"))\n',
    )
    assert rule_hits(catalogues.run(make_ctx(mod, readme="")), "SIMC")
    assert not rule_hits(catalogues.run(make_ctx(mod, readme="| ghost-scenario |")), "SIMC")


def test_resc_drift_and_guard():
    mod = (
        "tpu_scheduler/runtime/resilience.py",
        "DEFAULT_POLICIES = {\"ghost-class\": None}\n"
        "STATES = (\"closed\", \"ghost-state\")\n"
        "class BreakerConfig:\n    ghost_knob: int = 1\n",
    )
    hits = rule_hits(catalogues.run(make_ctx(mod, readme="closed")), "RESC")
    assert {h.message.split("'")[1] for h in hits} == {"ghost-class", "ghost-state", "ghost_knob"}
    ok_readme = "closed ghost-class ghost-state ghost_knob"
    assert not rule_hits(catalogues.run(make_ctx(mod, readme=ok_readme)), "RESC")


def test_topo_drift_and_guard():
    model_mod = (
        "tpu_scheduler/topology/model.py",
        'DEFAULT_LEVEL_KEYS = (("ghost-level", "topology.x/ghost-key"),)\n',
    )
    knob_mod = ("tpu_scheduler/topology/locality.py", 'SCORING_KNOBS = ("ghost_knob",)\n')
    sc_mod = (
        "tpu_scheduler/sim/scenarios.py",
        '_register(Scenario(name="ghost-topo-scenario", workload=WorkloadSpec(rack_size=4)))\n'
        '_register(Scenario(name="plain-scenario", workload=WorkloadSpec(arrival_rate=1.0)))\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(model_mod, knob_mod, sc_mod, readme="")), "TOPO")
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-level",
        "topology.x/ghost-key",
        "ghost_knob",
        "ghost-topo-scenario",  # plain-scenario is SIMC's business, not TOPO's
    }
    ok = "ghost-level topology.x/ghost-key ghost_knob ghost-topo-scenario"
    assert not rule_hits(catalogues.run(make_ctx(model_mod, knob_mod, sc_mod, readme=ok)), "TOPO")


def test_topo_real_tree_is_catalogued():
    files = load_files(["tpu_scheduler/topology", "tpu_scheduler/sim/scenarios.py"])
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "TOPO")
    assert not hits, "; ".join(h.render() for h in hits)


def test_repl_drift_and_guard():
    shards_mod = (
        "tpu_scheduler/runtime/shards.py",
        'SHARD_LEASE_PREFIX = "ghost-shard-"\nREPLICA_LEASE_PREFIX = "ghost-presence-"\nOTHER = "not-a-prefix"\n',
    )
    multi_mod = ("tpu_scheduler/sim/multi.py", 'AVAILABILITY_FIELDS = ("ghost_takeover_field",)\n')
    sc_mod = (
        "tpu_scheduler/sim/scenarios.py",
        '_register(Scenario(name="ghost-replica-scenario", replicas=2))\n'
        '_register(Scenario(name="plain-scenario", workload=WorkloadSpec(arrival_rate=1.0)))\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(shards_mod, multi_mod, sc_mod, readme="")), "REPL")
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-shard-",
        "ghost-presence-",
        "ghost_takeover_field",
        "ghost-replica-scenario",  # plain-scenario is SIMC's business, not REPL's
    }
    ok = "ghost-shard- ghost-presence- ghost_takeover_field ghost-replica-scenario"
    assert not rule_hits(catalogues.run(make_ctx(shards_mod, multi_mod, sc_mod, readme=ok)), "REPL")


def test_repl_real_tree_is_catalogued():
    files = load_files(
        ["tpu_scheduler/runtime/shards.py", "tpu_scheduler/sim/multi.py", "tpu_scheduler/sim/scenarios.py"]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "REPL")
    assert not hits, "; ".join(h.render() for h in hits)


def test_prof_drift_and_guard():
    mod = (
        "tpu_scheduler/utils/profiler.py",
        'SPAN_CATALOGUE = ("ghost-span",)\n'
        'SLO_TIERS = (("ghost-tier", 100, 60.0),)\n'
        'OTHER = ("not-a-span",)\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(mod, readme="")), "PROF")
    assert {h.message.split("'")[1] for h in hits} == {"ghost-span", "ghost-tier"}
    ok = "ghost-span ghost-tier"
    assert not rule_hits(catalogues.run(make_ctx(mod, readme=ok)), "PROF")


def test_prof_real_tree_is_catalogued():
    files = load_files(["tpu_scheduler/utils/profiler.py"])
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "PROF")
    assert not hits, "; ".join(h.render() for h in hits)


def test_dlta_drift_and_guard():
    eng_mod = (
        "tpu_scheduler/delta/engine.py",
        'ESCALATION_REASONS = ("ghost-trigger",)\nOTHER = ("not-a-trigger",)\n',
    )
    sc_mod = (
        "tpu_scheduler/sim/scorecard.py",
        'INCREMENTAL_FIELDS = ("ghost_incremental_field",)\nSCORECARD_FIELDS = ("simc_business",)\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(eng_mod, sc_mod, readme="")), "DLTA")
    # simc_business is SIMC's token, not DLTA's; OTHER is not a catalogue tuple.
    assert {h.message.split("'")[1] for h in hits} == {"ghost-trigger", "ghost_incremental_field"}
    ok = "ghost-trigger ghost_incremental_field"
    assert not rule_hits(catalogues.run(make_ctx(eng_mod, sc_mod, readme=ok)), "DLTA")


def test_dlta_real_tree_is_catalogued():
    files = load_files(["tpu_scheduler/delta/engine.py", "tpu_scheduler/sim/scorecard.py"])
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "DLTA")
    assert not hits, "; ".join(h.render() for h in hits)


def test_rebl_drift_and_guard():
    planner_mod = (
        "tpu_scheduler/rebalance/planner.py",
        'MIGRATION_REASONS = ("ghost-migration-reason",)\n'
        'SKIP_REASONS = ("ghost-skip-reason",)\n'
        "class RebalanceConfig:\n    ghost_knob: int = 1\n"
        'OTHER = ("not-a-reason",)\n',
    )
    sc_mod = (
        "tpu_scheduler/sim/scorecard.py",
        'REBALANCE_FIELDS = ("ghost_rebalance_field",)\nSCORECARD_FIELDS = ("simc_business",)\n',
    )
    scen_mod = (
        "tpu_scheduler/sim/scenarios.py",
        '_register(Scenario(name="ghost-defrag-scenario", rebalance=True))\n'
        '_register(Scenario(name="plain-scenario", workload=WorkloadSpec(arrival_rate=1.0)))\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(planner_mod, sc_mod, scen_mod, readme="")), "REBL")
    # simc_business is SIMC's token and plain-scenario SIMC's scenario;
    # OTHER is not a taxonomy tuple — none of them are REBL's business.
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-migration-reason",
        "ghost-skip-reason",
        "ghost_knob",
        "ghost_rebalance_field",
        "ghost-defrag-scenario",
    }
    ok = "ghost-migration-reason ghost-skip-reason ghost_knob ghost_rebalance_field ghost-defrag-scenario"
    assert not rule_hits(catalogues.run(make_ctx(planner_mod, sc_mod, scen_mod, readme=ok)), "REBL")


def test_rebl_real_tree_is_catalogued():
    files = load_files(
        ["tpu_scheduler/rebalance/planner.py", "tpu_scheduler/sim/scorecard.py", "tpu_scheduler/sim/scenarios.py"]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "REBL")
    assert not hits, "; ".join(h.render() for h in hits)


def test_flet_drift_and_guard():
    keyer_mod = (
        "tpu_scheduler/fleet/keyer.py",
        'KEYER_MODES = ("ghost-keyer-mode",)\nOTHER = ("not-a-mode",)\n',
    )
    res_mod = (
        "tpu_scheduler/fleet/reservation.py",
        'RESERVATION_STATES = ("ghost-reservation-state",)\n'
        'GANG_RESERVATION_PREFIX = "ghost-gang-"\n'
        'NOT_A_LEASE = "plain-string"\n',
    )
    resize_mod = ("tpu_scheduler/fleet/resize.py", 'SHARD_MAP_LEASE = "ghost-shard-map"\n')
    hits = rule_hits(catalogues.run(make_ctx(keyer_mod, res_mod, resize_mod, readme="")), "FLET")
    # OTHER / NOT_A_LEASE are not catalogue constants — not FLET's business.
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-keyer-mode",
        "ghost-reservation-state",
        "ghost-gang-",
        "ghost-shard-map",
    }
    ok = "ghost-keyer-mode ghost-reservation-state ghost-gang- ghost-shard-map"
    assert not rule_hits(catalogues.run(make_ctx(keyer_mod, res_mod, resize_mod, readme=ok)), "FLET")


def test_flet_real_tree_is_catalogued():
    files = load_files(
        ["tpu_scheduler/fleet/keyer.py", "tpu_scheduler/fleet/reservation.py", "tpu_scheduler/fleet/resize.py"]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "FLET")
    assert not hits, "; ".join(h.render() for h in hits)


def test_lern_drift_and_guard():
    obj_mod = (
        "tpu_scheduler/learn/objective.py",
        'OBJECTIVE_COMPONENTS = (("ghost-objective-component", 1.0),)\n'
        'POLICY_FIELDS = ("ghost_policy_field",)\n'
        'OTHER = ("not-a-component",)\n',
    )
    env_mod = (
        "tpu_scheduler/learn/env.py",
        'OBSERVATION_FIELDS = ("ghost_observation_field",)\n'
        'ACTION_KNOBS = (("ghost_action_knob", 0.0, 1.0),)\n',
    )
    search_mod = (
        "tpu_scheduler/learn/search.py",
        "class SearchConfig:\n    ghost_search_knob: int = 3\n\n\nclass Other:\n    not_a_knob: int = 1\n",
    )
    prof_mod = (
        "tpu_scheduler/models/profiles.py",
        'ARTIFACT_FIELDS = ("ghost_artifact_field",)\nNOT_AN_ENVELOPE = ("plain",)\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(obj_mod, env_mod, search_mod, prof_mod, readme="")), "LERN")
    # OTHER / Other.not_a_knob / NOT_AN_ENVELOPE are not catalogue surface.
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-objective-component",
        "ghost_policy_field",
        "ghost_observation_field",
        "ghost_action_knob",
        "ghost_search_knob",
        "ghost_artifact_field",
    }
    ok = (
        "ghost-objective-component ghost_policy_field ghost_observation_field "
        "ghost_action_knob ghost_search_knob ghost_artifact_field"
    )
    assert not rule_hits(catalogues.run(make_ctx(obj_mod, env_mod, search_mod, prof_mod, readme=ok)), "LERN")


def test_lern_real_tree_is_catalogued():
    files = load_files(
        [
            "tpu_scheduler/learn/objective.py",
            "tpu_scheduler/learn/env.py",
            "tpu_scheduler/learn/search.py",
            "tpu_scheduler/models/profiles.py",
        ]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "LERN")
    assert not hits, "; ".join(h.render() for h in hits)


def test_latn_drift_and_guard():
    events_mod = (
        "tpu_scheduler/utils/events.py",
        'SEGMENTS = ("ghost-segment",)\nEVENT_KINDS = ("not-a-segment",)\n',
    )
    sc_mod = (
        "tpu_scheduler/sim/scorecard.py",
        'LATENCY_FIELDS = ("ghost_latency_field",)\nOTHER_FIELDS = ("plain",)\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(events_mod, sc_mod, readme="")), "LATN")
    # EVENT_KINDS / OTHER_FIELDS are not LATN catalogue surface.
    assert {h.message.split("'")[1] for h in hits} == {"ghost-segment", "ghost_latency_field"}
    ok = "ghost-segment ghost_latency_field"
    assert not rule_hits(catalogues.run(make_ctx(events_mod, sc_mod, readme=ok)), "LATN")


def test_latn_real_tree_is_catalogued():
    files = load_files(["tpu_scheduler/utils/events.py", "tpu_scheduler/sim/scorecard.py"])
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "LATN")
    assert not hits, "; ".join(h.render() for h in hits)


def test_elas_drift_and_guard():
    policy_mod = (
        "tpu_scheduler/autoscale/policy.py",
        'SKIP_REASONS = ("ghost-elas-skip",)\n'
        "class AutoscaleConfig:\n    ghost_elas_knob: int = 1\n"
        'OTHER = ("not-a-reason",)\n',
    )
    provider_mod = (
        "tpu_scheduler/autoscale/provider.py",
        'DEFAULT_CATALOG = (InstanceSKU(name="ghost-sku", cpu=8),)\n'
        'OTHER = InstanceSKU(cpu=8)\n',
    )
    sc_mod = (
        "tpu_scheduler/sim/scorecard.py",
        'ELASTICITY_FIELDS = ("ghost_elasticity_field",)\nSCORECARD_FIELDS = ("simc_business",)\n',
    )
    scen_mod = (
        "tpu_scheduler/sim/scenarios.py",
        '_register(Scenario(name="ghost-elastic-scenario", autoscale=True))\n'
        '_register(Scenario(name="plain-scenario", workload=WorkloadSpec(arrival_rate=1.0)))\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(policy_mod, provider_mod, sc_mod, scen_mod, readme="")), "ELAS")
    # simc_business is SIMC's token and plain-scenario SIMC's scenario;
    # OTHER and the name-less InstanceSKU are not ELAS catalogue surface.
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-elas-skip",
        "ghost_elas_knob",
        "ghost-sku",
        "ghost_elasticity_field",
        "ghost-elastic-scenario",
    }
    ok = "ghost-elas-skip ghost_elas_knob ghost-sku ghost_elasticity_field ghost-elastic-scenario"
    assert not rule_hits(catalogues.run(make_ctx(policy_mod, provider_mod, sc_mod, scen_mod, readme=ok)), "ELAS")


def test_elas_real_tree_is_catalogued():
    files = load_files(
        [
            "tpu_scheduler/autoscale/policy.py",
            "tpu_scheduler/autoscale/provider.py",
            "tpu_scheduler/sim/scorecard.py",
            "tpu_scheduler/sim/scenarios.py",
        ]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "ELAS")
    assert not hits, "; ".join(h.render() for h in hits)


def test_fuzz_drift_and_guard():
    plan_mod = (
        "tpu_scheduler/sim/fuzz/plan.py",
        'FAULT_OPS = ("ghost-meteor",)\n'
        'PLAN_FIELDS = ("ghost_plan_field",)\n'
        'OP_FIELDS = ("ghost_op_field",)\n'
        'BASE_WORKLOADS = {"ghost-base": None}\n'
        'OTHER = ("not-a-fault",)\n',
    )
    cov_mod = (
        "tpu_scheduler/sim/fuzz/coverage.py",
        'STATE_FACETS = ("ghost-facet",)\n',
    )
    corpus_mod = (
        "tpu_scheduler/sim/fuzz/corpus.py",
        'ENTRY_FIELDS = ("ghost_entry_field",)\n',
    )
    sc_mod = (
        "tpu_scheduler/sim/scorecard.py",
        'CONVERGENCE_FIELDS = ("ghost_convergence_field",)\nSCORECARD_FIELDS = ("simc_business",)\n',
    )
    hits = rule_hits(catalogues.run(make_ctx(plan_mod, cov_mod, corpus_mod, sc_mod, readme="")), "FUZZ")
    # simc_business belongs to SIMC; OTHER is not fuzz catalogue surface.
    assert {h.message.split("'")[1] for h in hits} == {
        "ghost-meteor",
        "ghost_plan_field",
        "ghost_op_field",
        "ghost-base",
        "ghost-facet",
        "ghost_entry_field",
        "ghost_convergence_field",
    }
    ok = (
        "ghost-meteor ghost_plan_field ghost_op_field ghost-base "
        "ghost-facet ghost_entry_field ghost_convergence_field"
    )
    assert not rule_hits(catalogues.run(make_ctx(plan_mod, cov_mod, corpus_mod, sc_mod, readme=ok)), "FUZZ")


def test_fuzz_real_tree_is_catalogued():
    files = load_files(
        [
            "tpu_scheduler/sim/fuzz/plan.py",
            "tpu_scheduler/sim/fuzz/coverage.py",
            "tpu_scheduler/sim/fuzz/corpus.py",
            "tpu_scheduler/sim/scorecard.py",
        ]
    )
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    hits = rule_hits(catalogues.run(ctx), "FUZZ")
    assert not hits, "; ".join(h.render() for h in hits)


def test_anlz_drift_and_guard():
    codes = sorted(all_codes())
    partial_readme = " ".join(c for c in codes if c != "DTRM")
    hits = rule_hits(catalogues.run(make_ctx(readme=partial_readme)), "ANLZ")
    assert len(hits) == 1 and "'DTRM'" in hits[0].message
    assert not rule_hits(catalogues.run(make_ctx(readme=" ".join(codes))), "ANLZ")


# -- THRD lock discipline ---------------------------------------------------

THRD_BAD = """import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []  # guarded-by: _lock

    def bad(self):
        self.items.append(2)
"""

THRD_GOOD = """import threading


class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self.items = []  # guarded-by: _lock

    def good(self):
        with self._lock:
            self.items.append(1)

    def good_via_condition(self):
        with self._cv:
            self.items.append(2)

    def helper(self):  # holds-lock: _lock
        self.items.clear()

    def good_call(self):
        with self._lock:
            self.helper()
"""


def test_thrd_unguarded_access_caught_but_old_lint_passed():
    ctx = make_ctx(("tpu_scheduler/runtime/c.py", THRD_BAD))
    assert not legacy_findings(ctx), "the old lint.py rule set must pass this snippet"
    hits = rule_hits(locks.run(ctx), "THRD")
    assert len(hits) == 1 and "'items'" in hits[0].message and "outside" in hits[0].message


def test_thrd_guards_with_block_condition_alias_and_holds_lock():
    ctx = make_ctx(("tpu_scheduler/runtime/c.py", THRD_GOOD))
    assert not rule_hits(locks.run(ctx), "THRD")


def test_thrd_holds_lock_call_site_check():
    code = THRD_GOOD + "\n    def bad_call(self):\n        self.helper()\n"
    hits = rule_hits(locks.run(make_ctx(("tpu_scheduler/runtime/c.py", code))), "THRD")
    assert len(hits) == 1 and "helper()" in hits[0].message


def test_thrd_plain_lock_reentry_is_deadlock():
    code = (
        "import threading\n\n\nclass C:\n"
        "    def __init__(self):\n        self._lock = threading.Lock()\n"
        "    def boom(self):\n        with self._lock:\n            with self._lock:\n                pass\n"
    )
    hits = rule_hits(locks.run(make_ctx(("m.py", code))), "THRD")
    assert len(hits) == 1 and "deadlock" in hits[0].message
    # RLock re-entry is legal — the guard case.
    hits = rule_hits(locks.run(make_ctx(("m.py", code.replace("Lock()", "RLock()")))), "THRD")
    assert not hits


def test_thrd_lock_order_cycle_detection_and_guard():
    cyclic = """import threading


class A:
    def __init__(self):
        self._a_lock = threading.Lock()
        self.b = None

    def one(self):
        with self._a_lock:
            self.b.two()


class B:
    def __init__(self):
        self._b_lock = threading.Lock()
        self.a = None

    def two(self):
        with self._b_lock:
            pass

    def three(self):
        with self._b_lock:
            self.a.one()
"""
    hits = rule_hits(locks.run(make_ctx(("m.py", cyclic))), "THRD")
    assert len(hits) == 1 and "cycle" in hits[0].message
    # Consistent order (B.three not taking A's lock) — no cycle.
    acyclic = cyclic.replace("    def three(self):\n        with self._b_lock:\n            self.a.one()\n", "")
    assert not rule_hits(locks.run(make_ctx(("m.py", acyclic))), "THRD")


def test_thrd_dataclass_field_annotations():
    code = """import threading
from dataclasses import dataclass, field


@dataclass
class R:
    counters: dict = field(default_factory=dict)  # guarded-by: _lock
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def bad(self):
        return len(self.counters)
"""
    hits = rule_hits(locks.run(make_ctx(("m.py", code))), "THRD")
    assert len(hits) == 1 and "'counters'" in hits[0].message


# -- JAXP jit purity --------------------------------------------------------

JAXP_BAD = """import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


@partial(jax.jit, static_argnames=("n",))
def root(x, n):
    y = jnp.sum(x)
    if y > 0:
        return helper(y)
    return y


def helper(y):
    print(y)
    t = time.monotonic()
    z = np.asarray(y)
    return float(jnp.abs(y)) + y.item() + t + z
"""

JAXP_GOOD = """import jax
import jax.numpy as jnp
from functools import partial


@partial(jax.jit, static_argnames=("use_fast", "n"))
def root(x, use_fast, n):
    if use_fast:
        return jnp.sum(x[:n])
    return jnp.sum(x)


def host_driver(x):
    out = root(x, True, 4)
    return float(out) + out.item()
"""


def test_jaxp_host_syncs_caught_but_old_lint_passed():
    ctx = make_ctx(("tpu_scheduler/ops/m.py", JAXP_BAD))
    assert not legacy_findings(ctx), "the old lint.py rule set must pass this snippet"
    messages = [f.message for f in rule_hits(jitpure.run(ctx), "JAXP")]
    assert any("Python 'if' on a traced expression" in m for m in messages)
    assert any(".item() host sync" in m for m in messages)
    assert any("print() host I/O" in m for m in messages)
    assert any("time.monotonic() wall-clock" in m for m in messages)
    assert any("np.asarray() materializes a tracer" in m for m in messages)
    assert any("float() on a traced expression" in m for m in messages)


def test_jaxp_static_branches_and_host_code_not_flagged():
    ctx = make_ctx(("tpu_scheduler/ops/m.py", JAXP_GOOD))
    # Static-arg branches inside jit and syncs in UNreached host code are fine.
    assert not rule_hits(jitpure.run(ctx), "JAXP")


def test_jaxp_reaches_through_jax_jit_call_form():
    code = """import jax


def build():
    def inner(x):
        return x.item()

    return inner


fn = jax.jit(build())
"""
    hits = rule_hits(jitpure.run(make_ctx(("tpu_scheduler/ops/m.py", code))), "JAXP")
    assert len(hits) == 1 and ".item()" in hits[0].message


# -- DTRM sim determinism ---------------------------------------------------

DTRM_BAD = """import random
import time


def f(out):
    t = time.time()
    r = random.random()
    for x in {1, 2, 3}:
        out.append(x)
    return t, r
"""

DTRM_GOOD = """import random


def f(clock, rng: random.Random, out):
    t = clock()
    r = rng.random()
    seeded = random.Random(42).random()
    for x in sorted({1, 2, 3}):
        out.append(x)
    return t, r, seeded
"""


def test_dtrm_wall_clock_rng_and_set_iteration_caught_but_old_lint_passed():
    ctx = make_ctx(("tpu_scheduler/sim/mod.py", DTRM_BAD))
    assert not legacy_findings(ctx), "the old lint.py rule set must pass this snippet"
    messages = [f.message for f in rule_hits(determinism.run(ctx), "DTRM")]
    assert any("time.time()" in m for m in messages)
    assert any("random.random()" in m for m in messages)
    assert any("iteration over a set" in m for m in messages)
    assert len(messages) == 3


def test_dtrm_sanctioned_sources_not_flagged():
    assert not determinism.run(make_ctx(("tpu_scheduler/sim/mod.py", DTRM_GOOD)))


def test_dtrm_scoped_to_sim_package():
    # The same violations OUTSIDE sim/ are not DTRM's business.
    assert not determinism.run(make_ctx(("tpu_scheduler/runtime/mod.py", DTRM_BAD)))


# -- SHPE shape/dtype contracts ---------------------------------------------

SHPE_TRANSPOSED = """import jax.numpy as jnp


# shape: (mask: [P, N] bool, scores: [P, N] f32) -> [P] i64
def pick(mask, scores):
    s = jnp.where(mask, scores, -jnp.inf)
    return jnp.argmax(s, axis=1)


# shape: (mask: [P, N] bool, scores: [N, P] f32) -> [P] i64
def caller(mask, scores):
    return pick(mask, scores)
"""

SHPE_AXIS = """# shape: (scores: [P] f32) -> scalar f32
def total(scores):
    return scores.sum(axis=1)
"""

SHPE_BOOL_PROMO = """# shape: (mask: [P, N] bool, w: [P, N] f32) -> [P, N] f32
def weight(xp, mask, w):
    return mask * w
"""

SHPE_MATMUL = """# shape: (pod_sel: [P, L] f32, node_labels: [N, L] f32) -> [P, N] f32
def counts(pod_sel, node_labels):
    return pod_sel @ node_labels
"""

SHPE_CLEAN = """import jax.numpy as jnp


# shape: (mask: [P, N] bool, scores: [P, N] f32) -> [P] i64
def pick(mask, scores):
    s = jnp.where(mask, scores, -jnp.inf)
    return jnp.argmax(s, axis=1)


# shape: (mask: [P, N] bool, scores: [P, N] f32, w: [P, N] f32,
#   pod_sel: [P, L] f32, node_labels: [N, L] f32) -> [P] i64
def caller(mask, scores, w, pod_sel, node_labels):
    hits = pod_sel @ node_labels.T
    boosted = scores + w * mask.astype(jnp.float32) + hits
    return pick(mask, boosted)
"""


def shpe_hits(*files):
    return rule_hits(shapes.run(make_ctx(*files)), "SHPE")


def test_shpe_transposed_call_arg_caught_once_but_old_lint_passed():
    ctx = make_ctx(("tpu_scheduler/ops/m.py", SHPE_TRANSPOSED))
    assert not legacy_findings(ctx), "the old lint.py rule set must pass this snippet"
    hits = rule_hits(shapes.run(ctx), "SHPE")
    assert len(hits) == 1 and "transposed operand" in hits[0].message


def test_shpe_wrong_reduction_axis_caught_once():
    hits = shpe_hits(("tpu_scheduler/ops/m.py", SHPE_AXIS))
    assert len(hits) == 1 and "axis=1" in hits[0].message and "rank 1" in hits[0].message


def test_shpe_bool_mask_promotion_caught_once():
    hits = shpe_hits(("tpu_scheduler/ops/m.py", SHPE_BOOL_PROMO))
    assert len(hits) == 1 and "bool mask" in hits[0].message
    # explicit astype is the sanctioned form
    fixed = SHPE_BOOL_PROMO.replace("mask * w", "mask.astype(xp.float32) * w")
    assert not shpe_hits(("tpu_scheduler/ops/m.py", fixed))


def test_shpe_matmul_inner_mismatch_caught_once():
    hits = shpe_hits(("tpu_scheduler/ops/m.py", SHPE_MATMUL))
    assert len(hits) == 1 and "matmul inner dims differ" in hits[0].message
    fixed = SHPE_MATMUL.replace("pod_sel @ node_labels", "pod_sel @ node_labels.T")
    assert not shpe_hits(("tpu_scheduler/ops/m.py", fixed))


def test_shpe_return_drift_caught():
    code = "# shape: (x: [P, N] f32) -> [P] f32\ndef f(x):\n    return x\n"
    hits = shpe_hits(("tpu_scheduler/ops/m.py", code))
    assert len(hits) == 1 and "returns rank-2" in hits[0].message


def test_shpe_contract_rot_caught():
    code = (
        "# shape: (x: [P] floof) -> [P] f32\ndef f(x):\n    return x\n\n\n"
        "# shape: (ghost: [P] f32) -> [P] f32\ndef g(x):\n    return x\n"
    )
    msgs = [h.message for h in shpe_hits(("tpu_scheduler/ops/m.py", code))]
    assert any("malformed shape contract" in m for m in msgs)
    assert any("unknown parameter 'ghost'" in m for m in msgs)


def test_shpe_clean_pipeline_not_flagged():
    assert not shpe_hits(("tpu_scheduler/ops/m.py", SHPE_CLEAN))


def test_shpe_scalar_param_names_tie_allocation_shapes():
    code = (
        "import numpy as np\n\n\n"
        "# shape: (p_pad: int, t_pad: int) -> [p_pad, t_pad] f32\n"
        "def alloc(p_pad, t_pad):\n    return np.zeros((t_pad, p_pad), dtype=np.float32)\n"
    )
    hits = shpe_hits(("tpu_scheduler/ops/m.py", code))
    assert len(hits) == 1 and "returns [t_pad, p_pad]" in hits[0].message


def test_shpe_real_annotated_modules_are_clean():
    """FP guard over the real annotated tree: the tensor pipeline's ~75
    contracts must interpret clean (the acceptance bar for SHPE)."""
    files = load_files(
        [
            "tpu_scheduler/ops",
            "tpu_scheduler/core/predicates.py",
            "tpu_scheduler/backends",
            "tpu_scheduler/parallel/sharded.py",
            "tpu_scheduler/topology",
        ]
    )
    ctx = Context(files=files, root=ROOT, readme="")
    assert sum("# shape:" in f.text for f in files) >= 8, "annotated modules went missing"
    hits = rule_hits(shapes.run(ctx), "SHPE")
    assert not hits, "; ".join(h.render() for h in hits)


def test_shpe_topology_gather_contract_mutation_caught():
    """ISSUE 6 satellite: mutation-check a topology contract — dropping the
    per-pod gang-row gather in score_block ([G, N] broadcast straight into
    the [B, N] score) must contradict the declared `# shape:` contract."""
    path = ROOT / "tpu_scheduler" / "ops" / "score.py"
    text = path.read_text()
    ctx = make_ctx(("tpu_scheduler/ops/score.py", text))
    assert not rule_hits(shapes.run(ctx), "SHPE")
    mutated = text.replace("score + topo_gang_node[pod_gang_id]", "score + topo_gang_node")
    assert mutated != text, "the topology gather went missing from score_block"
    hits = rule_hits(shapes.run(make_ctx(("tpu_scheduler/ops/score.py", mutated))), "SHPE")
    assert len(hits) == 1 and "[G, N]" in hits[0].message and "[B, N]" in hits[0].message


# -- EXCP failure-class taxonomy closure ------------------------------------

EXCP_CONTROLLER = '''class Scheduler:
    @staticmethod
    def _requeue_reason_class(reason):
        if isinstance(reason, NoNodeFound):
            return "no-node"
        s = str(reason)
        head = s.split(":", 1)[0]
        if head in ("api-error", "network-error"):
            return head
        if "gang" in s:
            return "gang"
        return "other"
'''

EXCP_RESILIENCE = """DEFAULT_POLICIES = {
    "no-node": None,
    "api-error": None,
    "network-error": None,
    "gang": None,
    "other": None,
}
"""

EXCP_README = """| `scheduler_requeues_by_reason_total{reason=...}` | counter | `no-node`, `api-error`, `network-error`, `gang`, `other` |
| `no-node` | base | 4xbase |
| `api-error` | base/8 | 2xbase |
| `network-error` | base/8 | 2xbase |
| `gang` | base | 4xbase |
| `other` | base | 2xbase |
"""


def excp_ctx(controller=EXCP_CONTROLLER, resilience=EXCP_RESILIENCE, readme=EXCP_README):
    return make_ctx(
        ("tpu_scheduler/runtime/controller.py", controller),
        ("tpu_scheduler/runtime/resilience.py", resilience),
        readme=readme,
    )


def test_excp_closed_taxonomy_not_flagged():
    assert not rule_hits(excp.run(excp_ctx()), "EXCP")


def test_excp_missing_backoff_policy_caught_once_but_old_lint_passed():
    ctx = excp_ctx(controller=EXCP_CONTROLLER.replace('"gang"\n        return "other"', '"ghost-class"\n        return "other"'))
    assert not legacy_findings(ctx), "the old lint.py rule set must pass this snippet"
    hits = rule_hits(excp.run(ctx), "EXCP")
    policy_gaps = [h for h in hits if "has no BackoffQueue policy" in h.message]
    assert len(policy_gaps) == 1 and "'ghost-class'" in policy_gaps[0].message
    # the dropped class now also reads as a dead policy — the reverse gap
    assert any("never produced" in h.message and "'gang'" in h.message for h in hits)


def test_excp_dead_policy_caught():
    res = EXCP_RESILIENCE.replace('"other": None,', '"other": None,\n    "zombie": None,')
    hits = rule_hits(excp.run(excp_ctx(resilience=res)), "EXCP")
    assert len(hits) >= 1 and any("'zombie'" in h.message and "never produced" in h.message for h in hits)


def test_excp_readme_rows_required_both_tables():
    # strip the Resilience table row for gang: metric row keeps it
    readme = EXCP_README.replace("| `gang` | base | 4xbase |\n", "")
    hits = rule_hits(excp.run(excp_ctx(readme=readme)), "EXCP")
    assert len(hits) == 1 and "Resilience failure-class table" in hits[0].message and "'gang'" in hits[0].message
    # strip it from the metric row too
    readme2 = readme.replace("`gang`, ", "")
    hits2 = rule_hits(excp.run(excp_ctx(readme=readme2)), "EXCP")
    assert {h.message for h in hits2} > {h.message for h in hits}
    assert any("metric catalogue row" in h.message and "'gang'" in h.message for h in hits2)


def test_excp_silent_on_partial_context():
    """Without controller.py + resilience.py together the closure is
    unjudgeable — the pass must stay silent (the --changed-only contract)."""
    ctx = make_ctx(("tpu_scheduler/runtime/controller.py", EXCP_CONTROLLER), readme="")
    assert not excp.run(ctx)


def test_excp_real_tree_is_closed():
    files = load_files(["tpu_scheduler/runtime/controller.py", "tpu_scheduler/runtime/resilience.py"])
    ctx = Context(files=files, root=ROOT, readme=(ROOT / "README.md").read_text())
    hits = rule_hits(excp.run(ctx), "EXCP")
    assert not hits, "; ".join(h.render() for h in hits)


# -- baseline contract ------------------------------------------------------


def test_baseline_matches_current_findings_exactly():
    """baseline.json must pin exactly the findings the tree produces: no new
    findings, no stale entries — and zero DTRM entries in sim/ (the
    simulator is held to a clean bill, never a pinned one)."""
    files = load_files(DEFAULT_PATHS)
    readme = (ROOT / "README.md").read_text()
    ctx = Context(files=files, root=ROOT, readme=readme)
    findings = run_passes(ctx)
    entries = load_baseline()
    scope = {f.rel for f in files} | {"README.md"}
    new, stale, baselined = compare(findings, entries, paths=scope)
    assert not new, "unpinned findings: " + "; ".join(f.render() for f in new)
    assert not stale, "stale baseline entries: " + json.dumps(stale)
    assert len(baselined) == len(findings)
    assert not [
        e for e in entries if e["rule"] == "DTRM" and e["path"].startswith("tpu_scheduler/sim/")
    ], "DTRM findings in sim/ must be fixed, never baselined"
    for e in entries:
        assert len(e["reason"]) >= 20, f"baseline reasons must justify, not gesture: {e}"


def test_baseline_compare_new_and_stale_detection():
    from scripts.analyze.core import Finding

    found = [Finding("THRD", "a.py", 3, "msg-a")]
    entries = [
        {"rule": "THRD", "path": "a.py", "message": "msg-a", "reason": "pinned"},
        {"rule": "DTRM", "path": "b.py", "message": "msg-gone", "reason": "pinned"},
    ]
    new, stale, baselined = compare(found + [Finding("JAXP", "c.py", 1, "msg-new")], entries)
    assert [f.rule for f in new] == ["JAXP"]
    assert [e["rule"] for e in stale] == ["DTRM"]
    assert [f.rule for f in baselined] == ["THRD"]
    # Line numbers are not identity: a moved finding stays pinned.
    new, stale, _ = compare([Finding("THRD", "a.py", 99, "msg-a")], entries[:1])
    assert not new and not stale


# -- driver + shim ----------------------------------------------------------


def run_cli(*args):
    return subprocess.run(
        [sys.executable, *args], cwd=ROOT, capture_output=True, text=True, timeout=300
    )


def test_driver_exits_zero_on_tree():
    proc = run_cli("-m", "scripts.analyze")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_shim_still_works():
    proc = run_cli("scripts/lint.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "analyze:" in proc.stdout


def test_driver_rule_filter_and_json_output():
    proc = run_cli("-m", "scripts.analyze", "--rule", "THRD", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert {"files", "findings", "new", "stale", "elapsed_s", "budget_s", "changed_only", "modelcheck", "jitc"} == set(report)
    assert report["new"] == [] and report["stale"] == []
    assert all(f["rule"] == "THRD" for f in report["findings"])
    assert all(f["baselined"] for f in report["findings"])
    assert report["modelcheck"] == {}  # MODL did not run under --rule THRD
    assert report["jitc"] == {}  # JITC did not run under --rule THRD


def test_driver_rejects_unknown_rule():
    proc = run_cli("-m", "scripts.analyze", "--rule", "NOPE")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_driver_list_rules_covers_every_pass():
    proc = run_cli("-m", "scripts.analyze", "--list-rules")
    assert proc.returncode == 0
    for p in PASSES:
        for code in p.CODES:
            assert code in proc.stdout


def test_every_pass_declares_file_scoped():
    for p in PASSES:
        assert isinstance(getattr(p, "FILE_SCOPED", None), bool), p.__name__
    scoped = file_scoped_codes()
    # Cross-file rules must stay OUT of the --changed-only fast path: a
    # partial context would call a changed module's exports dead (DEAD) or
    # one taxonomy side missing (EXCP).
    assert "DEAD" not in scoped and "EXCP" not in scoped
    assert {"E999", "W291", "F401", "SHPE", "THRD", "DTRM"} <= scoped


def test_changed_paths_reads_git_status(tmp_path):
    import os
    import subprocess as sp

    repo = tmp_path / "r"
    repo.mkdir()
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t", "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"}
    sp.run(["git", "init", "-q"], cwd=repo, check=True, env=env)
    (repo / "clean.py").write_text("x = 1\n")
    (repo / "dirty.py").write_text("x = 1\n")
    sp.run(["git", "add", "-A"], cwd=repo, check=True, env=env)
    sp.run(["git", "commit", "-qm", "seed"], cwd=repo, check=True, env=env)
    (repo / "dirty.py").write_text("x = 2\n")  # unstaged modification
    (repo / "fresh.py").write_text("y = 1\n")  # untracked
    (repo / "notes.txt").write_text("ignored extension\n")
    assert changed_paths(repo) == ["dirty.py", "fresh.py"]


def test_driver_changed_only_fast_path_exits_zero():
    proc = run_cli("-m", "scripts.analyze", "--changed-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "changed-only" in proc.stdout or "0 changed files" in proc.stdout


def test_lint_shim_supports_changed_only():
    proc = run_cli("scripts/lint.py", "--changed-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_driver_budget_assertion():
    # An impossible budget must fail loudly...
    proc = run_cli("-m", "scripts.analyze", "--rule", "W291", "--budget", "0.000001")
    assert proc.returncode == 1
    assert "BUDGET EXCEEDED" in proc.stderr
    # ...and the real gate's 5s budget must hold on tier-1 hardware (the
    # ISSUE-5 wall-clock contract: analysis never becomes the slow part of
    # make check — the DEAD pass rewrite is what bought the headroom).
    proc = run_cli("-m", "scripts.analyze", "--budget", "5")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_driver_json_out_artifact(tmp_path):
    out = tmp_path / "report.json"
    proc = run_cli("-m", "scripts.analyze", "--rule", "SHPE", "--json-out", str(out))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["new"] == [] and report["stale"] == []
    assert isinstance(report["elapsed_s"], float)
    # the human summary still prints alongside the artifact
    assert "analyze:" in proc.stdout


# -- regression tests for the violations the suite surfaced -----------------


def test_flight_recorder_seen_is_atomic():
    """The pre-THRD ``seen`` probed membership under the lock, released it,
    then recorded — two racing threads could both miss the probe and
    double-record ``seen-pending``.  Now probe + append share one hold."""
    from tpu_scheduler.utils.events import FlightRecorder

    rec = FlightRecorder(max_pods=64)
    barrier = threading.Barrier(8)

    def hammer():
        barrier.wait()
        for _ in range(200):
            rec.seen("default/racer", 1)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tl = rec.timeline("default/racer")
    assert len(tl) == 1 and tl[0]["kind"] == "seen-pending"
    # And the single-threaded contract is unchanged: seen is once-only.
    rec.seen("default/racer", 2)
    assert len(rec.timeline("default/racer")) == 1


def test_tpu_backend_reads_variant_flags_under_guard_lock():
    """The pre-THRD ``assign`` read the proving/disable flags without the
    guard lock (a torn read against a concurrent strike).  Now the
    eligibility decision happens under ``_guard_lock``: a disabled variant
    is honored atomically, and assign genuinely serializes on the lock."""
    import types

    from tpu_scheduler.backends.tpu import TpuBackend

    b = TpuBackend(use_pallas=True)
    b._proven_variants.add(False)
    b._disabled_variants.add(False)  # proven once, then struck out
    seen = {}

    def fake_assign_once(packed, profile, use_pallas):
        seen["use_pallas"] = use_pallas
        return "ok"

    b._assign_once = fake_assign_once
    packed = types.SimpleNamespace(constraints=None)
    assert b.assign(packed, profile=None) == "ok"
    assert seen["use_pallas"] is False  # the disable was honored
    # assign must block while another thread holds the guard lock — the
    # pre-fix code skipped the lock entirely once a variant was proven.
    results = []
    assert b._guard_lock.acquire()
    t = threading.Thread(target=lambda: results.append(b.assign(packed, None)))
    t.start()
    t.join(0.3)
    try:
        assert t.is_alive(), "assign no longer takes the guard lock"
    finally:
        b._guard_lock.release()
        t.join(10)
    assert results == ["ok"]


def test_shpe_fused_filter_transposed_operand_caught():
    """ISSUE 9 satellite: mutation-check a fused-filter contract —
    transposing the spread-domain selection operand in
    _project_spread_domains ([D, C] fed as [C, D]) must contradict the
    declared `# shape:` contract via the matmul inner-dim check."""
    path = ROOT / "tpu_scheduler" / "ops" / "constraints.py"
    text = path.read_text()
    ctx = make_ctx(("tpu_scheduler/ops/constraints.py", text))
    assert not rule_hits(shapes.run(ctx), "SHPE")
    mutated = text.replace(
        "return nd @ sel, uses_sp @ sel, sp0 @ sel",
        "return nd @ sel, uses_sp @ sel.T, sp0 @ sel",
    )
    assert mutated != text, "the spread-domain projection went missing from constraints.py"
    hits = rule_hits(shapes.run(make_ctx(("tpu_scheduler/ops/constraints.py", mutated))), "SHPE")
    assert len(hits) == 1, "; ".join(h.render() for h in hits)
    assert "matmul inner dims differ" in hits[0].message and "[C, D]" in hits[0].message


def test_shpe_rebalance_fit_matrix_broadcast_caught():
    """ISSUE 11 satellite: mutation-check a rebalance/ contract — dropping
    the column-keeping subscript on the migration-diff operand in
    _fit_matrix (comparing the [N] budget column against the [1, M] victim
    row) must contradict the declared `# shape:` contract via the
    broadcast check."""
    path = ROOT / "tpu_scheduler" / "rebalance" / "solver.py"
    text = path.read_text()
    ctx = make_ctx(("tpu_scheduler/rebalance/solver.py", text))
    assert not rule_hits(shapes.run(ctx), "SHPE")
    mutated = text.replace(
        "budget[:, 0:1] >= req_cpu[None, :]",
        "budget[:, 0] >= req_cpu[None, :]",
    )
    assert mutated != text, "the fit matrix went missing from rebalance/solver.py"
    hits = rule_hits(shapes.run(make_ctx(("tpu_scheduler/rebalance/solver.py", mutated))), "SHPE")
    assert hits, "broadcast-conflicting fit matrix not caught"
    assert any("[N]" in h.message and "[1, M]" in h.message for h in hits), "; ".join(
        h.render() for h in hits
    )


def test_shpe_delta_candidate_mask_broadcast_caught():
    """ISSUE 10 satellite: mutation-check a delta/ contract — dropping the
    per-axis subscript on the min-request operand in _candidate_mask
    (comparing the [N] node column against the whole [R] vector) must
    contradict the declared `# shape:` contract via the broadcast check."""
    path = ROOT / "tpu_scheduler" / "delta" / "repack.py"
    text = path.read_text()
    ctx = make_ctx(("tpu_scheduler/delta/repack.py", text))
    assert not rule_hits(shapes.run(ctx), "SHPE")
    mutated = text.replace(
        "return valid & (avail[:, 0] >= min_req[0]) & (avail[:, 1] >= min_req[1])",
        "return valid & (avail[:, 0] >= min_req) & (avail[:, 1] >= min_req[1])",
    )
    assert mutated != text, "the candidate mask went missing from delta/repack.py"
    hits = rule_hits(shapes.run(make_ctx(("tpu_scheduler/delta/repack.py", mutated))), "SHPE")
    assert hits, "transposed/broadcast-conflicting candidate mask not caught"
    assert any("[N]" in h.message and "[R]" in h.message for h in hits), "; ".join(
        h.render() for h in hits
    )


# -- PROT protocol contracts + MODL model checking ---------------------------

from scripts.analyze import modelcheck, protocol  # noqa: E402

PROT_SYNTH = '''STATES = ("idle", "running", "done")


# protocol: machine widget field=state states=STATES init=idle
# protocol: idle -> running
# protocol: running -> done
# protocol: var work: 0..1 = 0
# protocol: action start: idle -> running effect work = 1
# protocol: action finish: running -> done effect work = 0
# protocol: invariant done-clean: state == done implies work == 0
class Widget:
    def __init__(self):
        self.state = "idle"

    def start(self):
        if self.state == "idle":
            self.state = "running"

    def finish(self):
        if self.state == "running":
            self.state = "done"
'''


def test_prot_clean_synthetic_machine_and_transition_mutations():
    ctx = make_ctx(("tpu_scheduler/w.py", PROT_SYNTH))
    assert not rule_hits(protocol.run(ctx), "PROT")
    # TP 1: an undeclared transition (done -> running restart).
    mutated = PROT_SYNTH.replace(
        'if self.state == "running":\n            self.state = "done"',
        'if self.state == "running":\n            self.state = "done"\n'
        '        elif self.state == "done":\n            self.state = "running"',
    )
    assert mutated != PROT_SYNTH
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/w.py", mutated))), "PROT")
    assert len(hits) == 1 and "undeclared transition done -> running" in hits[0].message
    # TP 2: a state name outside the closed vocabulary.
    mutated = PROT_SYNTH.replace('self.state = "done"', 'self.state = "finished"')
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/w.py", mutated))), "PROT")
    # the typo is flagged AND 'done' loses its only mention (coverage).
    assert any("'finished' is not a declared state" in h.message for h in hits)
    assert any("state 'done'" in h.message and "never used" in h.message for h in hits)
    # TP 3: __init__ drift against init=.
    mutated = PROT_SYNTH.replace('self.state = "idle"', 'self.state = "running"')
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/w.py", mutated))), "PROT")
    assert len(hits) == 1 and "__init__ sets 'running' but init=idle" in hits[0].message


def test_prot_sink_and_accessor_resolution():
    """The breaker shape: writes routed through a sink method and compares
    through an accessor alias are still transition-checked — no special
    cases, the promotion is simply a declared edge."""
    code = '''# protocol: machine m field=state init=a
# protocol: states: a | b | c
# protocol: a -> b
# protocol: b -> c
# protocol: action go: a -> b
# protocol: action fin: b -> c
# protocol: invariant vacuous: state != a or state == a
class M:
    def __init__(self):
        self.state = "a"

    def mode(self):
        if self.state == "a":
            return self.state
        return self.state

    def _transition(self, to):
        self.state = to

    def is_done(self):
        return self.state == "c"

    def poke(self):
        st = self.mode()
        if st == "a":
            self._transition("b")
'''
    # Clean ONLY because the accessor alias narrows the sink call's
    # from-set to {a}: un-narrowed, c -> b would be an undeclared edge.
    ctx = make_ctx(("tpu_scheduler/m.py", code))
    assert not rule_hits(protocol.run(ctx), "PROT")
    # Guarding the same sink call on the wrong branch is caught.
    bad = code.replace('if st == "a":', 'if st == "c":')
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/m.py", bad))), "PROT")
    assert len(hits) == 1 and "undeclared transition c -> b" in hits[0].message
    # And so is removing the guard entirely (the from-set widens to all).
    bad = code.replace('        st = self.mode()\n        if st == "a":\n            self._transition("b")',
                       '        self._transition("b")')
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/m.py", bad))), "PROT")
    assert len(hits) == 1 and "undeclared transition c -> b" in hits[0].message


def test_prot_seeded_provider_resurrect_caught_exactly_once():
    """ISSUE 18 satellite: the canonical seeded bug — a deleted->ready
    resurrect method in provider.py — must produce exactly one PROT
    finding naming the undeclared transition."""
    path = ROOT / "tpu_scheduler" / "autoscale" / "provider.py"
    text = path.read_text()
    rel = "tpu_scheduler/autoscale/provider.py"
    assert not rule_hits(protocol.run(make_ctx((rel, text))), "PROT")
    mutated = text.replace(
        "    def _kill(self, rec: dict, out: dict) -> bool:",
        '    def _resurrect(self, rec: dict) -> None:\n'
        '        if rec["state"] == "deleted":\n'
        '            rec["state"] = "ready"\n'
        "\n"
        "    def _kill(self, rec: dict, out: dict) -> bool:",
    )
    assert mutated != text, "_kill went missing from provider.py"
    hits = rule_hits(protocol.run(make_ctx((rel, mutated))), "PROT")
    assert len(hits) == 1, "; ".join(h.render() for h in hits)
    assert "undeclared transition deleted -> ready" in hits[0].message


def test_prot_keyed_counter_coverage_both_directions():
    """The RESERVATION_STATES exhaustiveness gate: a counts[] key outside
    the vocabulary is flagged, and dropping the only `expired` bump makes
    the member uncovered (the hand-maintained-in-parallel drift class)."""
    path = ROOT / "tpu_scheduler" / "fleet" / "reservation.py"
    text = path.read_text()
    rel = "tpu_scheduler/fleet/reservation.py"
    assert not rule_hits(protocol.run(make_ctx((rel, text))), "PROT")
    # Direction 1: a key the vocabulary does not declare.
    mutated = text.replace('self.counts["committed"] += 1', 'self.counts["comitted"] += 1')
    assert mutated != text
    hits = rule_hits(protocol.run(make_ctx((rel, mutated))), "PROT")
    assert any("'comitted' is not a declared state" in h.message for h in hits)
    assert any("state 'committed'" in h.message and "never used" in h.message for h in hits)
    # Direction 2: a declared member the class never touches.
    mutated = text.replace('self.counts["expired"] += 1', "pass")
    assert mutated != text
    hits = rule_hits(protocol.run(make_ctx((rel, mutated))), "PROT")
    assert len(hits) == 1 and "state 'expired'" in hits[0].message and "never used" in hits[0].message


def test_prot_taxonomy_membership_and_coverage(tmp_path):
    decl = '''# protocol: taxonomy REASONS producers=_skip scope=pkg
REASONS = ("alpha", "beta")
'''
    user_ok = '''class C:
    def _skip(self, reason):
        pass

    def f(self):
        self._skip("alpha")
        self._skip("beta")
        self._skip("beta" if self.x else "alpha")
'''
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "decl.py").write_text(decl)
    (pkg / "user.py").write_text(user_ok)

    def tax_ctx(decl_text, user_text):
        files = []
        for rel, code in (("pkg/decl.py", decl_text), ("pkg/user.py", user_text)):
            (tmp_path / rel).write_text(code)
            files.append(
                SourceFile(path=tmp_path / rel, rel=rel, text=code, lines=code.splitlines(), tree=ast.parse(code))
            )
        return Context(files=files, root=tmp_path, readme="")

    assert not rule_hits(protocol.run(tax_ctx(decl, user_ok)), "PROT")
    # Membership: a produced literal outside the tuple (IfExp branch too).
    bad = user_ok.replace('"beta" if self.x else "alpha"', '"gamma" if self.x else "alpha"')
    hits = rule_hits(protocol.run(tax_ctx(decl, bad)), "PROT")
    assert len(hits) == 1 and "'gamma'" in hits[0].message and "REASONS" in hits[0].message
    # Coverage: a member no producer ever emits (scope fully loaded).
    bad = user_ok.replace('self._skip("beta")\n        self._skip("beta" if self.x else "alpha")', "pass")
    assert bad != user_ok
    hits = rule_hits(protocol.run(tax_ctx(decl, bad)), "PROT")
    assert len(hits) == 1 and "member 'beta' is never produced" in hits[0].message


def test_prot_taxonomy_coverage_silent_on_partial_context(tmp_path):
    """--changed-only soundness: with part of the scope missing from the
    context, the coverage direction must stay silent, not lie."""
    decl = '''# protocol: taxonomy REASONS producers=_skip scope=pkg
REASONS = ("alpha", "beta")
'''
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "decl.py").write_text(decl)
    (pkg / "user.py").write_text("def f(_skip):\n    _skip('alpha')\n")
    files = [
        SourceFile(
            path=pkg / "decl.py", rel="pkg/decl.py", text=decl, lines=decl.splitlines(), tree=ast.parse(decl)
        )
    ]
    ctx = Context(files=files, root=tmp_path, readme="")
    assert not rule_hits(protocol.run(ctx), "PROT")


def test_prot_spec_errors_are_findings():
    bad = '''# protocol: machine m field=state init=a
# protocol: states: a | b
# protocol: a -> b
# protocol: action go: a -> c
# protocol: invariant x: bogus ~ 3
class M:
    def __init__(self):
        self.state = "a"
'''
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/m.py", bad))), "PROT")
    msgs = "; ".join(h.message for h in hits)
    assert "unknown state 'c'" in msgs and "bad condition atom" in msgs
    # And an action edge outside the declared relation is spec-inconsistent.
    bad2 = bad.replace("action go: a -> c", "action go: b -> a").replace("invariant x: bogus ~ 3", "invariant x: state == a")
    hits = rule_hits(protocol.run(make_ctx(("tpu_scheduler/m.py", bad2))), "PROT")
    assert any("undeclared transition b -> a" in h.message for h in hits)


def test_prot_real_tree_is_clean_with_all_six_sites():
    """FP guard over the real annotated tree (the acceptance bar): all six
    protocol sites parse, all three taxonomies parse, zero findings."""
    files = load_files(DEFAULT_PATHS)
    ctx = Context(files=files, root=ROOT, readme="")
    machines, taxes = [], []
    for f in ctx.parsed():
        specs, _ = protocol.collect_machines(f)
        machines.extend(s for s, _cls in specs)
        tx, _ = protocol.collect_taxonomies(f)
        taxes.extend(tx)
    assert {m.name for m in machines} >= {
        "circuit-breaker", "shard-lease", "gang-reservation",
        "drain-migration", "provider-node", "placement-ledger", "fuzz-plan",
    }
    assert len(taxes) >= 3
    hits = rule_hits(protocol.run(ctx), "PROT")
    assert not hits, "; ".join(h.render() for h in hits)


def _machine_from(rel, mutated_text):
    sf = SourceFile(
        path=ROOT / rel, rel=rel, text=mutated_text, lines=mutated_text.splitlines(), tree=ast.parse(mutated_text)
    )
    machines, errs = protocol.collect_machines(sf)
    assert not errs, "; ".join(e.render() for e in errs)
    assert len(machines) == 1
    return machines[0][0]


def _mutate_and_check(rel, old, new, prop):
    """Apply one contract mutation, model-check, and return the single
    violation of ``prop`` (asserting it is reported exactly once)."""
    text = (ROOT / rel).read_text()
    mutated = text.replace(old, new)
    assert mutated != text, f"contract line went missing from {rel}: {old!r}"
    clean = modelcheck.explore(_machine_from(rel, text))
    assert clean["violations"] == [], f"{rel} spec no longer verifies clean"
    result = modelcheck.explore(_machine_from(rel, mutated))
    hits = [v for v in result["violations"] if v[1] == prop]
    assert len(hits) == 1, f"{prop}: {result['violations']}"
    return hits[0]


def test_modl_breaker_double_bind_mutation_caught_once():
    """Dropping the overlay latch from defer lets the deferred pod place
    twice — the assumed-overlay double-bind the invariant exists for."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/runtime/resilience.py",
        "# protocol: action defer: open -> open requires pending == 1 and overlaid == 0 effect overlaid = 1, placed += 1",
        "# protocol: action defer: open -> open requires pending == 1 effect placed += 1",
        "no-double-bind",
    )
    assert kind == "invariant" and trace and trace.count("defer") >= 2


def test_modl_lease_release_is_final_mutation_caught_once():
    """Un-guarding the stale renew thread resurrects the PR-7 race: a
    voluntarily released lease gets re-acquired by the dead thread."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/runtime/shards.py",
        "# protocol: env thread-renew: free -> held requires released == 0",
        "# protocol: env thread-renew: free -> held",
        "release-is-final",
    )
    assert kind == "invariant" and trace == ["acquire", "release", "thread-renew"]


def test_modl_drain_orphan_mutation_caught_once():
    """Breaking unbind's atomic CAS (bound cleared without pending set)
    orphans the victim immediately — a one-step violating trace."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/rebalance/executor.py",
        "# protocol: action unbind: verify -> unbound requires bound == 1 effect bound = 0, pending = 1",
        "# protocol: action unbind: verify -> unbound requires bound == 1 effect bound = 0",
        "never-orphaned",
    )
    assert kind == "invariant" and trace == ["unbind"]


def test_modl_provider_delete_over_pod_mutation_caught_once():
    """Un-guarding kill deletes a node still holding a pod; the minimal
    trace walks the full lifecycle to the racing state."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/autoscale/provider.py",
        "# protocol: action kill: reclaiming -> deleted requires pods == 0",
        "# protocol: action kill: reclaiming -> deleted",
        "delete-only-when-empty",
    )
    assert kind == "invariant" and trace == ["join", "bind", "notice", "kill"]


def test_modl_ledger_flush_twice_mutation_caught_once():
    """Giving the duplicated commit delivery a capacity effect breaks
    exactly-once accounting — the two-phase-commit double-consume."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/delta/state.py",
        "# protocol: env dup-commit: committed -> committed",
        "# protocol: env dup-commit: committed -> committed effect used += 1",
        "flush-at-most-once",
    )
    assert kind == "invariant" and trace == ["commit", "dup-commit"]


def test_modl_trace_minimality_on_seeded_two_phase_commit_bug():
    """ISSUE 18 satellite: the trace-minimality contract.  Seeding the
    two-phase reservation protocol with a TTL that forgets to reclaim the
    peer leases must produce the MINIMAL trace — crash then ttl, exactly
    two environment steps, nothing extra prepended or interleaved."""
    kind, name, trace, _ = _mutate_and_check(
        "tpu_scheduler/fleet/reservation.py",
        "# protocol: env ttl: reserved -> expired requires alive == 0 effect leases = 0",
        "# protocol: env ttl: reserved -> expired requires alive == 0",
        "expired-clean",
    )
    assert kind == "invariant"
    assert trace == ["crash", "ttl"], f"non-minimal or non-deterministic trace: {trace}"


def test_modl_progress_violation_and_state_space_cap():
    # A machine whose declared-stuck state trips the progress property.
    stuck = '''# protocol: machine m field=- init=a
# protocol: states: a | b
# protocol: a -> b
# protocol: action go: a -> b
# protocol: progress alive: state == b
class M:
    pass
'''
    hits = rule_hits(modelcheck.run(make_ctx(("tpu_scheduler/m.py", stuck))), "MODL")
    assert len(hits) == 1 and "progress 'alive' stuck" in hits[0].message and "go" in hits[0].message
    # A runaway var blows the composite-space cap loudly, never hangs.
    runaway = '''# protocol: machine m field=- init=a
# protocol: states: a | b
# protocol: a -> b
# protocol: var x: 0..99999 = 0
# protocol: action inc: * -> * effect x += 1
# protocol: invariant fine: x >= 0
class M:
    pass
'''
    hits = rule_hits(modelcheck.run(make_ctx(("tpu_scheduler/m.py", runaway))), "MODL")
    assert len(hits) == 1 and "exceeds" in hits[0].message


def test_modl_real_tree_verifies_and_exports_stats():
    """The acceptance bar: every committed spec verifies against its
    environment, and LAST_STATS carries the per-machine evidence the
    driver folds into --json-out for bench.py provenance."""
    files = load_files(DEFAULT_PATHS)
    ctx = Context(files=files, root=ROOT, readme="")
    hits = rule_hits(modelcheck.run(ctx), "MODL")
    assert not hits, "; ".join(h.render() for h in hits)
    stats = modelcheck.LAST_STATS
    assert len(stats) >= 6
    for name, row in stats.items():
        assert row["states"] >= 2, f"{name} explores a vacuous space"
        assert row["violations"] == 0
        assert row["invariants"] + row["progress"] >= 1, f"{name} proves nothing"


def test_driver_json_out_carries_modelcheck_stats(tmp_path):
    out = tmp_path / "report.json"
    proc = run_cli("-m", "scripts.analyze", "--rule", "MODL", "--json-out", str(out), "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert len(report["modelcheck"]) >= 6
    assert all(m["violations"] == 0 for m in report["modelcheck"].values())


def test_prot_and_modl_are_registered_and_scoped():
    codes = all_codes()
    assert "PROT" in codes and "MODL" in codes
    # PROT rides --changed-only; MODL is full-context like EXCP.
    scoped = file_scoped_codes()
    assert "PROT" in scoped and "MODL" not in scoped


# -- JITC compile-cache boundedness + XFER host-sync discipline ---------------

from scripts.analyze import jitc  # noqa: E402


def test_jitc_pack_unbucket_mutation_caught_once():
    """ISSUE 20 acceptance: deleting one power-of-2 round-up under a real
    `# bucket:` contract in pack.py must produce EXACTLY one JITC finding
    (a raw per-cycle dim reaching the jit roots), and the committed file
    must be clean."""
    path = ROOT / "tpu_scheduler" / "ops" / "pack.py"
    text = path.read_text()
    ctx = make_ctx(("tpu_scheduler/ops/pack.py", text))
    assert not rule_hits(jitc.run(ctx), "JITC")
    mutated = text.replace("n_pad = round_up(n_real, node_block)", "n_pad = n_real")
    assert mutated != text, "the node-pad round-up went missing from pack_snapshot"
    hits = rule_hits(jitc.run(make_ctx(("tpu_scheduler/ops/pack.py", mutated))), "JITC")
    assert len(hits) == 1 and "n_pad" in hits[0].message and "raw per-cycle value" in hits[0].message


JITC_ROOT_BRANCH = '''from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_pad",))
def solve(req, n_pad, limit):
    if limit > 0:
        return jnp.sum(req[:n_pad])
    return jnp.sum(req)
'''


def test_jitc_traced_scalar_branch_caught_and_static_guard():
    """A Python branch on a per-call scalar inside a jit root retraces per
    value (or crashes on a traced array); promoting the name to
    static_argnames is the sanctioned spelling and must silence it."""
    ctx = make_ctx(("tpu_scheduler/ops/fixture.py", JITC_ROOT_BRANCH))
    hits = rule_hits(jitc.run(ctx), "JITC")
    assert len(hits) == 1 and "'limit'" in hits[0].message and "static_argnames" in hits[0].message
    fixed = JITC_ROOT_BRANCH.replace('static_argnames=("n_pad",)', 'static_argnames=("n_pad", "limit")')
    assert not rule_hits(jitc.run(make_ctx(("tpu_scheduler/ops/fixture.py", fixed))), "JITC")


XFER_HOTPATH = '''from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_pad",))
def solve(req, n_pad):
    return jnp.sum(req)


# hotpath: cycle-driver
def run_cycle(req):
    out = solve(req, n_pad=8)
    return out.item()
'''


def test_xfer_hotpath_item_sync_caught_and_declared_span_guard():
    """`.item()` on a jit-root result inside a `# hotpath:` function is a
    hidden per-cycle device round-trip; both sanctioned spellings — a
    `with span("...host-sync...")` block and a trailing `# host-sync:`
    justification — must silence it."""
    ctx = make_ctx(("tpu_scheduler/ops/fixture.py", XFER_HOTPATH))
    hits = rule_hits(jitc.run(ctx), "XFER")
    assert len(hits) == 1 and ".item()" in hits[0].message and "host-sync" in hits[0].message
    justified = XFER_HOTPATH.replace("return out.item()", "return out.item()  # host-sync: verdict fetch")
    assert not rule_hits(jitc.run(make_ctx(("tpu_scheduler/ops/fixture.py", justified))), "XFER")
    spanned = XFER_HOTPATH.replace(
        "    return out.item()",
        '    with span("solve/host-sync"):\n        return out.item()',
    )
    assert not rule_hits(jitc.run(make_ctx(("tpu_scheduler/ops/fixture.py", spanned))), "XFER")


def test_jitc_real_tree_is_clean_and_exports_stats():
    """FP guard over the real annotated tree: every committed `# bucket:`
    and `# hotpath:` contract must interpret clean, and LAST_STATS carries
    the coverage evidence the driver folds into --json-out for bench.py
    provenance."""
    files = load_files(DEFAULT_PATHS)
    ctx = Context(files=files, root=ROOT, readme="")
    n_bucket = sum(f.text.count("# bucket:") for f in files)
    n_hot = sum(f.text.count("# hotpath:") for f in files)
    assert n_bucket >= 9 and n_hot >= 5, "bucket/hotpath annotations went missing"
    hits = [f for f in jitc.run(ctx) if f.rule in ("JITC", "XFER")]
    assert not hits, "; ".join(h.render() for h in hits)
    stats = jitc.LAST_STATS
    assert stats["bucket_contracts"] >= 9
    assert stats["hotpath_contracts"] >= 5
    assert stats["jit_roots"] >= 5
    assert stats["root_call_sites"] >= 5
    assert stats["allowed_syncs"] >= 1


def test_driver_json_out_carries_jitc_stats(tmp_path):
    out = tmp_path / "report.json"
    proc = run_cli("-m", "scripts.analyze", "--rule", "JITC,XFER", "--json-out", str(out), "--no-baseline")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(out.read_text())
    assert report["jitc"]["bucket_contracts"] >= 9
    assert report["jitc"]["jit_roots"] >= 5


def test_jitc_and_xfer_are_registered_and_scoped():
    codes = all_codes()
    assert "JITC" in codes and "XFER" in codes
    # Both interpret per-module with unresolved imports trusted, so they
    # soundly ride the --changed-only fast path.
    scoped = file_scoped_codes()
    assert "JITC" in scoped and "XFER" in scoped
