"""Extended resources (kube device-plugin semantics: google.com/tpu,
nvidia.com/gpu, hugepages-*) — THE resource class a TPU-native scheduler
exists to place.  The reference ignores every name but cpu/memory
(src/util.rs:54-75); here they are first-class axes of the [·, R] packed
tensors, the scalar chain, preemption, and the fused Pallas kernel (up to 3
extended axes; wider clusters ride the jnp path)."""


import tpu_scheduler.core.predicates as P
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.backends.tpu import TpuBackend
from tpu_scheduler.core.snapshot import ClusterSnapshot
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.ops.pack import pack_snapshot, resource_vocab
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod, synth_cluster

TPU = "example.com/tpu"


def _accel_cluster():
    nodes = [
        make_node("gpu-1", cpu="16", memory="64Gi", extended={TPU: "8"}),
        make_node("gpu-2", cpu="16", memory="64Gi", extended={TPU: "4"}),
        make_node("plain", cpu="16", memory="64Gi"),
    ]
    return nodes


# --- scalar chain ------------------------------------------------------------


def test_scalar_fit_requires_extended_capacity():
    snap = ClusterSnapshot.build(_accel_cluster(), [])
    pod = make_pod("train", cpu="1", extended={TPU: "6"})
    fits = {n.name: P.pod_fits_resources(pod, n, snap) for n in snap.nodes}
    assert fits == {"gpu-1": True, "gpu-2": False, "plain": False}


def test_scalar_usage_subtracts():
    snap = ClusterSnapshot.build(
        _accel_cluster(),
        [make_pod("running", cpu="1", extended={TPU: "6"}, node_name="gpu-1", phase="Running")],
    )
    pod = make_pod("train", cpu="1", extended={TPU: "4"})
    fits = {n.name: P.pod_fits_resources(pod, n, snap) for n in snap.nodes}
    assert fits == {"gpu-1": False, "gpu-2": True, "plain": False}


# --- tensor path -------------------------------------------------------------


def test_pack_builds_resource_vocab_and_r3_tensors():
    snap = ClusterSnapshot.build(
        _accel_cluster(),
        [make_pod("train", cpu="1", extended={TPU: "4"})],
    )
    assert resource_vocab(snap) == ("cpu", "memory", TPU)
    packed = pack_snapshot(snap)
    assert packed.res_vocab == ("cpu", "memory", TPU)
    assert packed.pod_req.shape[1] == 3 and packed.node_avail.shape[1] == 3
    assert packed.pod_req[0, 2] == 4
    by = {n: i for i, n in enumerate(packed.node_names)}
    assert packed.node_avail[by["gpu-1"], 2] == 8
    assert packed.node_avail[by["plain"], 2] == 0


def test_backend_parity_and_placement():
    pods = [make_pod(f"train-{i}", cpu="1", memory="1Gi", extended={TPU: "4"}) for i in range(3)]
    snap = ClusterSnapshot.build(_accel_cluster(), pods)
    packed = pack_snapshot(snap)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rt.bindings
    # capacity: 8 + 4 chips -> exactly three 4-chip pods, none on 'plain'
    assert len(rn.bindings) == 3
    assert all(nn != "plain" for _, nn in rn.bindings)
    per_node = {}
    for _, nn in rn.bindings:
        per_node[nn] = per_node.get(nn, 0) + 4
    assert per_node.get("gpu-1", 0) <= 8 and per_node.get("gpu-2", 0) <= 4


def test_oversubscription_impossible():
    """9 single-chip pods onto 8+4 chips: at most 12 chips' worth binds and
    no node exceeds its chip count."""
    pods = [make_pod(f"t-{i}", cpu="100m", memory="128Mi", extended={TPU: "2"}) for i in range(9)]
    snap = ClusterSnapshot.build(_accel_cluster(), pods)
    packed = pack_snapshot(snap)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rt.bindings
    used = {}
    for _, nn in rn.bindings:
        used[nn] = used.get(nn, 0) + 2
    assert used.get("gpu-1", 0) <= 8 and used.get("gpu-2", 0) <= 4 and "plain" not in used
    assert len(rn.bindings) == 6  # 12 chips / 2 per pod


def test_pallas_interpret_parity_r3():
    """The fused kernel's extended-fit rows, in interpreter mode (CPU)."""
    pods = [make_pod(f"t-{i}", cpu="500m", memory="512Mi", extended={TPU: str(1 + i % 4)}) for i in range(24)]
    snap = ClusterSnapshot.build(_accel_cluster() + [make_node("gpu-3", cpu="16", memory="64Gi", extended={TPU: "8"})], pods)
    packed = pack_snapshot(snap)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rp = TpuBackend(use_pallas=True).schedule(packed, DEFAULT_PROFILE.with_(driver="monolithic"))
    assert rn.bindings == rp.bindings


def test_sharded_parity_r3():
    from tpu_scheduler.parallel.sharded import ShardedBackend

    pods = [make_pod(f"t-{i}", cpu="500m", memory="512Mi", extended={TPU: str(1 + i % 3)}) for i in range(40)]
    nodes = [make_node(f"g{i}", cpu="32", memory="128Gi", extended={TPU: "8"}) for i in range(8)]
    snap = ClusterSnapshot.build(nodes, pods)
    packed = pack_snapshot(snap)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rs = ShardedBackend(tp=2).schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rs.bindings


def test_preemption_frees_chips():
    """A high-priority trainer evicts a low-priority chip hog — the deficit
    accounting must see the CHIP axis, not just cpu/memory."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("gpu-1", cpu="16", memory="64Gi", extended={TPU: "8"})],
        pods=[
            make_pod("hog", cpu="1", memory="1Gi", extended={TPU: "8"}, node_name="gpu-1", phase="Running", priority=0),
            make_pod("urgent", cpu="1", memory="1Gi", extended={TPU: "8"}, priority=100),
        ],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, profile=DEFAULT_PROFILE.with_(preemption=True))
    m = sched.run_cycle()
    assert m.bound == 1
    names = {p.metadata.name: p.spec.node_name for p in api.list_pods()}
    assert names == {"urgent": "gpu-1"}


def test_synth_extended_parity_and_validity():
    from tpu_scheduler.api.objects import total_pod_resources
    from tpu_scheduler.core.snapshot import node_allocatable

    for seed in (2, 9):
        snap = synth_cluster(n_nodes=24, n_pending=150, n_bound=24, seed=seed, extended_fraction=0.3)
        packed = pack_snapshot(snap)
        rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
        rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
        assert rn.bindings == rt.bindings, f"seed {seed}"
        # chips never oversubscribed (generator's bound pods carry none)
        node_by = {n.name: n for n in snap.nodes}
        pending = snap.pending_pods()
        chip_used: dict[str, int] = {}
        for i, pod in enumerate(pending):
            j = int(rn.assigned[i])
            if j < 0:
                continue
            r = total_pod_resources(pod)
            if r.extended:
                nn = packed.node_names[j]
                chip_used[nn] = chip_used.get(nn, 0) + r.extended.get(TPU, 0)
        for name, used in chip_used.items():
            cap = (node_allocatable(node_by[name]).extended or {}).get(TPU, 0)
            assert used <= cap, f"{name} chips oversubscribed (seed {seed}): {used} > {cap}"


def test_manifest_extended_round_trip():
    from tpu_scheduler.api.objects import Pod, pod_to_dict

    pod = make_pod("t", extended={TPU: "4"})
    back = Pod.from_dict(pod_to_dict(pod))
    from tpu_scheduler.api.objects import total_pod_resources

    assert total_pod_resources(back).extended == {TPU: 4}


def test_hugepages_bytes_scale_without_saturation():
    """Review repro: >=2 GiB hugepages quantities must not saturate int32 —
    byte-valued columns ride KiB scaling like memory (floor avail / ceil
    req), so the tensor path stays exact."""
    nodes = [
        make_node("big", cpu="16", memory="64Gi", extended={"hugepages-2Mi": "4Gi"}),
        make_node("small", cpu="16", memory="64Gi", extended={"hugepages-2Mi": "1Gi"}),
    ]
    pods = [make_pod("user", cpu="1", memory="1Gi", extended={"hugepages-2Mi": "3Gi"})]
    snap = ClusterSnapshot.build(nodes, pods)
    packed = pack_snapshot(snap)
    assert packed.node_avail[0, 2] == 4 * 1024 * 1024  # KiB, exact
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rt.bindings == [("default/user", "big")]


def test_kube_native_names_stay_ignored():
    """Review repro: ephemeral-storage (and other kube-native non-device
    names) must not make pods unschedulable on nodes that don't report it."""
    from tpu_scheduler.api.objects import Pod

    pod = Pod.from_dict(
        {
            "kind": "Pod",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {
                "containers": [
                    {
                        "name": "main",
                        "resources": {"requests": {"cpu": "1", "memory": "1Gi", "ephemeral-storage": "1Gi"}},
                    }
                ]
            },
        }
    )
    snap = ClusterSnapshot.build([make_node("n1", cpu="8", memory="32Gi")], [pod])
    assert P.pod_fits_resources(pod, snap.nodes[0], snap)
    packed = pack_snapshot(snap)
    assert packed.res_vocab == ("cpu", "memory")
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == [("default/web", "n1")]


def test_byte_valued_non_hugepages_resource_does_not_saturate():
    """Review repro (sgx.intel.com/epc): any byte-valued extended resource
    gets a value-derived column divisor, so >=2 GiB quantities never clamp
    into a false fit — the tensor path agrees with the scalar oracle."""
    epc = "sgx.intel.com/epc"
    nodes = [make_node("n1", cpu="16", memory="64Gi", extended={epc: "3Gi"})]
    pods = [make_pod("p", cpu="1", memory="1Gi", extended={epc: "4Gi"})]
    snap = ClusterSnapshot.build(nodes, pods)
    assert not P.pod_fits_resources(pods[0], nodes[0], snap)
    packed = pack_snapshot(snap)
    assert packed.res_scales[2] > 1  # value-derived divisor kicked in
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rt.bindings == []
    # and a genuinely fitting request still binds
    snap2 = ClusterSnapshot.build(nodes, [make_pod("q", cpu="1", memory="1Gi", extended={epc: "2Gi"})])
    packed2 = pack_snapshot(snap2)
    assert NativeBackend().schedule(packed2, DEFAULT_PROFILE).bindings == [("default/q", "n1")]


def test_kubernetes_io_domain_is_not_extended():
    """Review repro: *.kubernetes.io/* names are NOT extended resources
    (kube IsExtendedResourceName) — requesting one must not gate scheduling."""
    from tpu_scheduler.api.objects import is_extended_resource

    assert not is_extended_resource("something.kubernetes.io/foo")
    assert not is_extended_resource("kubernetes.io/batteries")
    assert is_extended_resource("google.com/tpu")
    assert is_extended_resource("hugepages-2Mi")
    pod = make_pod("p", cpu="1", memory="1Gi", extended={"something.kubernetes.io/foo": "1"})
    snap = ClusterSnapshot.build([make_node("n1", cpu="8", memory="32Gi")], [pod])
    assert P.pod_fits_resources(pod, snap.nodes[0], snap)
    packed = pack_snapshot(snap)
    assert packed.res_vocab == ("cpu", "memory")
    assert NativeBackend().schedule(packed, DEFAULT_PROFILE).bindings == [("default/p", "n1")]


def test_oversized_memory_clamps_without_breaking_incremental():
    """Review repro: a >2 TiB-KiB memory request keeps the documented clamp
    (cpu/memory scales are fixed) — it must NOT force a full repack every
    cycle."""
    from tpu_scheduler.ops.pack import repack_incremental

    nodes = [make_node("n1", cpu="8", memory="32Gi")]
    snap = ClusterSnapshot.build(nodes, [make_pod("small", cpu="1", memory="1Gi")])
    packed = pack_snapshot(snap)
    snap2 = ClusterSnapshot.build(
        nodes, [make_pod("small", cpu="1", memory="1Gi"), make_pod("huge", cpu="1", memory="3Ti")]
    )
    packed2 = repack_incremental(packed, snap2)  # must not raise
    assert packed2.pod_req[:, 1].max() == 2**31 - 1  # clamped, unschedulable
    rn = NativeBackend().schedule(packed2, DEFAULT_PROFILE)
    assert ("default/huge", "n1") not in rn.bindings


def test_exact_boundary_request_never_false_fits():
    """Review repro: a request of INT32_MAX*scale + 1 must escalate the
    divisor (ceil-consistent scale selection), never clamp into a fit."""
    epc = "sgx.intel.com/epc"
    cap = (2**31 - 1) * 1  # node capacity = INT32_MAX units at scale 1
    nodes = [make_node("n1", cpu="8", memory="32Gi", extended={epc: str(cap)})]
    pod = make_pod("p", cpu="1", memory="1Gi", extended={epc: str(cap + 1)})
    snap = ClusterSnapshot.build(nodes, [pod])
    assert not P.pod_fits_resources(pod, nodes[0], snap)
    packed = pack_snapshot(snap)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    rt = TpuBackend().schedule(packed, DEFAULT_PROFILE)
    assert rn.bindings == rt.bindings == []


def test_sample_policy_respects_extended_and_pod_affinity():
    """The faithful ≤5-random-candidates policy shares _check_with_ledger,
    so chips and co-location gate it identically to the batch path."""
    from tpu_scheduler.api.objects import PodAffinityTerm

    api = FakeApiServer()
    api.load(
        nodes=[
            make_node("gpu-z1", cpu="16", memory="64Gi", labels={"zone": "z1"}, extended={TPU: "8"}),
            make_node("gpu-z2", cpu="16", memory="64Gi", labels={"zone": "z2"}, extended={TPU: "8"}),
            make_node("plain", cpu="16", memory="64Gi", labels={"zone": "z2"}),
        ],
        pods=[
            make_pod("anchor", cpu="1", labels={"app": "cache"}, node_name="gpu-z1", phase="Running"),
            make_pod(
                "train",
                cpu="1",
                extended={TPU: "4"},
                labels={"app": "train"},
                pod_affinity=[PodAffinityTerm(match_labels={"app": "cache"}, topology_key="zone")],
            ),
        ],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, policy="sample", attempts=50)
    m = sched.run_cycle()
    assert m.bound == 1
    train = next(p for p in api.list_pods() if p.metadata.name == "train")
    assert train.spec.node_name == "gpu-z1"  # only node with chips AND in the anchor's zone


def test_new_extended_name_mid_run_forces_full_repack():
    """A pod requesting a never-seen device name widens every [·,R] tensor —
    the incremental path must degrade to a full pack (counter) and the pod
    must schedule correctly against the widened tensors."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("gpu-1", cpu="16", memory="64Gi", extended={TPU: "8", "vendor.example/fpga": "2"})],
        pods=[make_pod("a", cpu="1", memory="1Gi", extended={TPU: "2"})],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.run_cycle()
    full0 = sched.metrics.snapshot()["scheduler_full_packs_total"]
    api.create_pod(make_pod("b", cpu="1", memory="1Gi", extended={TPU: "1"}))
    sched.run_cycle()  # same vocab: incremental
    assert sched.metrics.snapshot()["scheduler_full_packs_total"] == full0
    api.create_pod(make_pod("c", cpu="1", memory="1Gi", extended={"vendor.example/fpga": "1"}))
    sched.run_cycle()  # new name -> vocab change -> full pack
    assert sched.metrics.snapshot()["scheduler_full_packs_total"] == full0 + 1
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods() if p.spec.node_name}
    assert placed == {"a": "gpu-1", "b": "gpu-1", "c": "gpu-1"}
    # and the widened pack keeps incremental service afterwards
    api.create_pod(make_pod("d", cpu="1", memory="1Gi", extended={"vendor.example/fpga": "1"}))
    sched.run_cycle()
    assert sched.metrics.snapshot()["scheduler_full_packs_total"] == full0 + 1
