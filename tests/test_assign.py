"""Batched assignment properties: validity (never oversubscribes, selector
respected), completeness (−1 only when truly infeasible), priority order,
determinism.  Run on the native backend; parity with TPU is in
test_backends_parity.py.
"""

import numpy as np
import pytest

from tpu_scheduler import ClusterSnapshot
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.core.predicates import node_selector_matches
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.ops.pack import pack_snapshot
from tpu_scheduler.testing import make_node, make_pod, synth_cluster


def check_validity(snap, packed, result):
    """Assignments never oversubscribe any node and respect selectors; −1 pods
    are infeasible against the remaining capacity."""
    pending = snap.pending_pods()
    nodes = list(snap.nodes)
    committed = np.zeros((packed.padded_nodes, 2), dtype=np.int64)
    for i, j in enumerate(result.assigned):
        if j >= 0:
            committed[j] += packed.pod_req[i]
            assert node_selector_matches(pending[i], nodes[j]), (pending[i].name, nodes[j].name)
    remaining = packed.node_avail.astype(np.int64) - committed
    assert (remaining[: packed.num_nodes] >= np.minimum(packed.node_avail[: packed.num_nodes], 0)).all(), (
        "oversubscribed a node"
    )
    # Every unscheduled pod is infeasible against what's left.
    for i, j in enumerate(result.assigned):
        if j < 0:
            pod = pending[i]
            for k, node in enumerate(nodes):
                fits = (packed.pod_req[i] <= remaining[k]).all()
                assert not (fits and node_selector_matches(pod, node)), (
                    f"pod {pod.name} left unscheduled but feasible on {node.name}"
                )


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("shape", [(5, 8), (20, 60), (40, 300)])
def test_validity_properties(seed, shape):
    n_nodes, n_pending = shape
    snap = synth_cluster(n_nodes=n_nodes, n_pending=n_pending, n_bound=n_nodes * 2, seed=seed)
    packed = pack_snapshot(snap, pod_block=32, node_block=8)
    result = NativeBackend().schedule(packed, DEFAULT_PROFILE.with_(max_rounds=256))
    assert len(result.bindings) + len(result.unschedulable) == packed.num_pods
    check_validity(snap, packed, result)


def test_all_fit_when_capacity_ample():
    snap = synth_cluster(n_nodes=20, n_pending=30, seed=3, selector_fraction=0.0)
    packed = pack_snapshot(snap)
    result = NativeBackend().schedule(packed)
    assert result.unschedulable == []
    assert len(result.bindings) == 30


def test_contention_single_node():
    # One node, 4 cores; six 1-core pods → exactly 4 bind, highest priority first.
    node = make_node("n0", cpu="4", memory="64Gi")
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi", priority=i) for i in range(6)]
    snap = ClusterSnapshot.build([node], pods)
    packed = pack_snapshot(snap)
    result = NativeBackend().schedule(packed)
    assert len(result.bindings) == 4
    bound = {name.split("/")[-1] for name, _ in result.bindings}
    assert bound == {"p2", "p3", "p4", "p5"}  # priorities 2..5 win
    assert {n.split("/")[-1] for n in result.unschedulable} == {"p0", "p1"}


def test_fifo_tiebreak_within_priority():
    node = make_node("n0", cpu="2", memory="64Gi")
    pods = [make_pod(f"p{i}", cpu="1", memory="1Gi", priority=0) for i in range(4)]
    snap = ClusterSnapshot.build([node], pods)
    result = NativeBackend().schedule(pack_snapshot(snap))
    bound = {name.split("/")[-1] for name, _ in result.bindings}
    assert bound == {"p0", "p1"}  # FIFO within equal priority


def test_selector_routes_to_matching_node():
    nodes = [
        make_node("gpu-1", cpu="8", memory="32Gi", labels={"pool": "gpu"}),
        make_node("cpu-1", cpu="8", memory="32Gi", labels={"pool": "cpu"}),
    ]
    pods = [make_pod("want-gpu", cpu="1", memory="1Gi", node_selector={"pool": "gpu"})]
    result = NativeBackend().schedule(pack_snapshot(ClusterSnapshot.build(nodes, pods)))
    assert result.bindings == [("default/want-gpu", "gpu-1")]


def test_unschedulable_selector():
    nodes = [make_node("n0", cpu="8", memory="32Gi", labels={"zone": "a"})]
    pods = [make_pod("p", cpu="1", memory="1Gi", node_selector={"zone": "nowhere"})]
    result = NativeBackend().schedule(pack_snapshot(ClusterSnapshot.build(nodes, pods)))
    assert result.bindings == []
    assert result.unschedulable == ["default/p"]


def test_big_pod_does_not_block_small():
    # Big pod (5 cores) can never fit; small pods behind it in priority order
    # must still bind (prefix-greedy recovers across rounds).
    node = make_node("n0", cpu="4", memory="64Gi")
    pods = [
        make_pod("big", cpu="5", memory="1Gi", priority=10),
        make_pod("small1", cpu="2", memory="1Gi", priority=1),
        make_pod("small2", cpu="2", memory="1Gi", priority=0),
    ]
    result = NativeBackend().schedule(pack_snapshot(ClusterSnapshot.build([node], pods)))
    bound = {name.split("/")[-1] for name, _ in result.bindings}
    assert bound == {"small1", "small2"}
    assert [n.split("/")[-1] for n in result.unschedulable] == ["big"]


def test_deterministic():
    snap = synth_cluster(n_nodes=30, n_pending=100, seed=7)
    packed = pack_snapshot(snap)
    r1 = NativeBackend().schedule(packed)
    r2 = NativeBackend().schedule(packed)
    assert (r1.assigned == r2.assigned).all()


def test_empty_cluster():
    snap = ClusterSnapshot.build([], [make_pod("p")])
    result = NativeBackend().schedule(pack_snapshot(snap))
    assert result.bindings == []
    assert result.unschedulable == ["default/p"]


def test_no_pending_pods():
    snap = ClusterSnapshot.build([make_node("n")], [])
    result = NativeBackend().schedule(pack_snapshot(snap))
    assert result.bindings == [] and result.unschedulable == []
    assert result.rounds == 0


# --- epoch-shrinking driver (perf path of TpuBackend) ------------------------


@pytest.mark.parametrize(
    "n_nodes,n_pending,seed,kw",
    [
        (16, 200, 0, {}),  # contention: many rounds, several shrinks
        (64, 500, 1, {"selector_fraction": 0.4}),
        (24, 120, 2, {"soft_taint_fraction": 0.3, "preferred_affinity_fraction": 0.3}),
        (24, 160, 5, {"extended_fraction": 0.3}),  # [·,3] resource tensors
    ],
)
def test_epoch_driver_matches_monolithic(n_nodes, n_pending, seed, kw):
    """assign_cycle_epochs must be bit-identical to assign_cycle: same
    assignments, same rounds, same remaining capacity, same acc_round."""
    import jax.numpy as jnp

    from tpu_scheduler.models.profiles import DEFAULT_PROFILE
    from tpu_scheduler.ops.assign import assign_cycle, assign_cycle_epochs, split_device_arrays

    snap = synth_cluster(n_nodes=n_nodes, n_pending=n_pending, n_bound=n_nodes, seed=seed, **kw)
    packed = pack_snapshot(snap, pod_block=16, node_block=16)
    a = {k: jnp.asarray(v) for k, v in packed.device_arrays().items()}
    nodes, pods = split_device_arrays(a)
    w = jnp.asarray(DEFAULT_PROFILE.weights())
    mono = assign_cycle(nodes, pods, w, max_rounds=64, block=32)
    epoch = assign_cycle_epochs(nodes, pods, w, max_rounds=64, block=32)
    np.testing.assert_array_equal(np.asarray(mono[0]), np.asarray(epoch[0]))  # assigned
    assert int(mono[1]) == int(epoch[1])  # rounds
    np.testing.assert_array_equal(np.asarray(mono[2]), np.asarray(epoch[2]))  # avail
    np.testing.assert_array_equal(np.asarray(mono[3]), np.asarray(epoch[3]))  # acc_round
    np.testing.assert_array_equal(np.asarray(mono[4]), np.asarray(epoch[4]))  # rank_of


def test_epoch_driver_matches_monolithic_constrained():
    """Constraint cycles (AA + spread + ScheduleAnyway) through the epoch
    driver: identical to the monolithic path and the native oracle."""
    from dataclasses import replace

    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE
    from tpu_scheduler.ops.constraints import pack_constraints

    snap = synth_cluster(
        n_nodes=24, n_pending=160, n_bound=24, seed=4,
        anti_affinity_fraction=0.2, spread_fraction=0.2, schedule_anyway_fraction=0.2,
    )
    packed = pack_snapshot(snap)
    cons = pack_constraints(snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes)
    assert cons is not None
    packed = replace(packed, constraints=cons)
    rn = NativeBackend().schedule(packed, DEFAULT_PROFILE)
    for driver in ("monolithic", "epochs"):
        rt = TpuBackend().schedule(packed, DEFAULT_PROFILE.with_(driver=driver))
        assert rn.bindings == rt.bindings, driver
        assert rn.rounds == rt.rounds, driver
        assert (rn.stats["acc_round"] == rt.stats["acc_round"]).all(), driver


def test_throughput_profile_round_count_stays_low():
    """Round-5 regression guard: bucket-quantized tie-breaking spreads the
    claimant herd across the whole near-tie band, collapsing the flagship
    auction from 9 rounds to 2.  Pin the effect at a moderate shape — a
    tie-break regression (e.g. reverting to additive jitter) re-herds the
    claims and pushes the round count back up."""
    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    snap = synth_cluster(n_nodes=1000, n_pending=10_000, n_bound=2_000, seed=0)
    packed = pack_snapshot(snap, pod_block=4096, node_block=128)
    r = NativeBackend().schedule(packed, PROFILES["throughput"])
    assert len(r.bindings) == 10_000
    assert r.rounds <= 4, f"tie-break regression: {r.rounds} rounds at 10k x 1k"
