"""Loop-resilience tests: watch backoff, transport-error requeue, daemon
mode, and full recovery after the remote API server dies and comes back —
the reference's survival contract (src/main.rs:136-139: watch errors are
dropped and the stream reconnects with exponential backoff; main.rs:122-125:
per-pod failures requeue instead of crashing)."""

import threading

import pytest

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import ApiError, FakeApiServer
from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient, RemoteApiAdapter
from tpu_scheduler.runtime.reflector import Reflector
from tpu_scheduler.testing import make_node, make_pod


class FlakyWatch:
    """Watch whose poll() raises for the first ``fail_times`` calls."""

    def __init__(self, events, fail_times=0, exc=ConnectionError("boom")):
        self._events = list(events)
        self.fail_times = fail_times
        self.exc = exc
        self.polls = 0

    def poll(self):
        self.polls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise self.exc
        out, self._events = self._events, []
        return out


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _watch_events(objs):
    from tpu_scheduler.runtime.fake_api import WatchEvent

    return [WatchEvent("ADDED", o) for o in objs]


# --- Reflector backoff -------------------------------------------------------


def test_reflector_survives_transient_poll_errors():
    clock = FakeClock()
    watch = FlakyWatch(_watch_events([make_node("n1")]), fail_times=2)
    r = Reflector(watch, key_fn=lambda n: n.name, clock=clock)
    assert r.sync() == []  # failure 1: swallowed
    assert r.errors_seen == 1
    assert r.last_error is not None
    # In backoff window: no poll attempt at all.
    polls_before = watch.polls
    assert r.sync() == []
    assert watch.polls == polls_before
    # Advance past the backoff: failure 2, then success.
    clock.t += 100.0
    assert r.sync() == []
    assert r.errors_seen == 2
    clock.t += 100.0
    events = r.sync()
    assert len(events) == 1
    assert r.state()[0].name == "n1"
    assert r.errors_seen == 2


def test_reflector_backoff_grows_then_resets():
    clock = FakeClock()
    watch = FlakyWatch([], fail_times=5)
    r = Reflector(watch, key_fn=lambda n: n.name, clock=clock, backoff_initial=1.0, backoff_max=8.0)
    delays = []
    for _ in range(5):
        r.sync()
        delays.append(r._retry_at - clock.t)
        clock.t = r._retry_at + 0.001
    # Exponential growth (jittered into [b/2, b]) capped at backoff_max.
    assert delays[0] <= 1.0
    assert delays[2] > delays[0]
    assert all(d <= 8.0 for d in delays)
    r.sync()  # success resets
    assert r._backoff == 0.0


def test_reflector_api_error_also_swallowed():
    clock = FakeClock()
    watch = FlakyWatch([], fail_times=1, exc=ApiError(503, "unavailable"))
    r = Reflector(watch, key_fn=lambda n: n.name, clock=clock)
    assert r.sync() == []
    assert r.errors_seen == 1


def test_scheduler_counts_watch_errors_in_metrics():
    api = FakeApiServer()
    api.load(nodes=[make_node("n1")], pods=[make_pod("p1")])
    sched = Scheduler(api, NativeBackend())
    # Wrap the node watch in a flaky layer after construction.
    real_watch = sched.reflector.nodes._watch
    flaky = FlakyWatch([], fail_times=1)

    def poll():
        if flaky.fail_times > 0:
            flaky.fail_times -= 1
            raise ConnectionError("watch down")
        return real_watch.poll()

    flaky.poll = poll
    sched.reflector.nodes._watch = flaky
    m = sched.run_cycle()
    assert sched.metrics.snapshot().get("scheduler_watch_errors_total") == 1
    # Cycle completed despite the watch failure (on empty last-known state).
    assert m.cycle == 1


# --- content-hash node signature (no resourceVersion on the wire) ------------


def test_node_signature_detects_change_without_resource_version():
    api = FakeApiServer()
    n = make_node("n1", labels={"zone": "a"})
    n.metadata.resource_version = 0
    api.load(nodes=[n], pods=[])
    sched = Scheduler(api, NativeBackend())
    sched.reflector.sync()
    sig1 = sched.reflector.node_set_signature()
    # Mutate labels in place but keep rv=0 (remote servers that omit rv).
    n2 = make_node("n1", labels={"zone": "b"})
    n2.metadata.resource_version = 0
    sched.reflector.nodes.store["n1"] = n2
    sig2 = sched.reflector.node_set_signature()
    assert sig1 != sig2


def test_node_signature_stable_for_same_content():
    a = make_node("n1", labels={"zone": "a"})
    a.metadata.resource_version = 0
    b = make_node("n1", labels={"zone": "a"})
    b.metadata.resource_version = 0
    from tpu_scheduler.runtime.reflector import _node_content_signature

    assert _node_content_signature(a) == _node_content_signature(b)


# --- daemon mode -------------------------------------------------------------


def test_daemon_mode_idles_instead_of_exiting():
    api = FakeApiServer()
    api.load(nodes=[make_node("n1")], pods=[make_pod("p1")])
    sched = Scheduler(api, NativeBackend())
    sleeps = []
    out = sched.run(max_cycles=4, daemon_interval=0.5, sleep=sleeps.append)
    assert len(out) == 4  # did NOT stop at the settled cycle
    assert sum(m.bound for m in out) == 1
    # Idle cycles (2..4 bind nothing) slept the interval.
    assert sleeps == [0.5, 0.5, 0.5]


def test_daemon_mode_stop_event():
    api = FakeApiServer()
    api.load(nodes=[make_node("n1")], pods=[])
    sched = Scheduler(api, NativeBackend())
    stop = threading.Event()
    calls = {"n": 0}
    orig = sched.run_cycle

    def counting():
        calls["n"] += 1
        if calls["n"] >= 3:
            stop.set()
        return orig()

    sched.run_cycle = counting
    sched.run(daemon_interval=0.01, stop_event=stop)
    assert calls["n"] == 3


def test_until_settled_does_not_settle_on_unhealthy_watch():
    """A transient watch outage at startup must not produce a silent
    'settled, bound nothing' exit-0 — the loop rides out the backoff and
    schedules once the watch recovers.  Backoff waits ride the sim's
    VirtualClock (clock + sleep injected), so the 0.5 s initial watch
    backoff costs zero wall time and the assertions stay exact."""
    from tpu_scheduler.sim import VirtualClock

    clock = VirtualClock()
    api = FakeApiServer(clock=clock)
    api.load(nodes=[make_node("n1")], pods=[make_pod("p1")])
    sched = Scheduler(api, NativeBackend(), clock=clock)
    real_watch = sched.reflector.pods._watch
    state = {"fails": 2}

    class Flaky:
        def poll(self):
            if state["fails"] > 0:
                state["fails"] -= 1
                raise ConnectionError("api server starting up")
            return real_watch.poll()

    sched.reflector.pods._watch = Flaky()
    out = sched.run(until_settled=True, sleep=clock.sleep)
    assert sum(m.bound for m in out) == 1  # p1 scheduled after recovery
    assert clock.now > 0.0  # the backoff windows were ridden out virtually


def test_until_settled_raises_on_persistent_outage():
    api = FakeApiServer()
    api.load(nodes=[make_node("n1")], pods=[])
    sched = Scheduler(api, NativeBackend())

    class Dead:
        def poll(self):
            raise ConnectionError("api server gone")

    sched.reflector.pods._watch = Dead()
    sched.reflector.nodes._watch = Dead()
    slept = {"t": 0.0}

    def fast_sleep(dt):
        slept["t"] += dt

    with pytest.raises(RuntimeError, match="unhealthy"):
        sched.run(until_settled=True, sleep=fast_sleep)


def test_daemon_history_bounded():
    api = FakeApiServer()
    api.load(nodes=[make_node("n1")], pods=[])
    sched = Scheduler(api, NativeBackend())
    out = sched.run(max_cycles=300, daemon_interval=0.0, sleep=lambda _: None)
    assert len(out) == 256


# --- end-to-end: API server dies mid-run and comes back ----------------------


def test_scheduler_survives_api_server_restart():
    """Kill the HTTP server under a live scheduler; it must keep cycling on
    last-known state (watch errors → metrics), then resume binding when a
    server comes back on the same port.  The scheduler runs on a
    VirtualClock, so the reflector backoff windows between cycles are
    advanced virtually instead of slept (was ~0.4 s + up to 2.5 s of real
    sleeps riding out real backoff)."""
    from tpu_scheduler.sim import VirtualClock

    clock = VirtualClock()
    api = FakeApiServer()
    api.load(nodes=[make_node("n1", cpu=32, memory="64Gi")], pods=[make_pod("p1")])
    server = HttpApiServer(api).start()
    host, port = server.address
    client = KubeApiClient(server.base_url)
    sched = Scheduler(RemoteApiAdapter(client), NativeBackend(), clock=clock)

    m1 = sched.run_cycle()
    assert m1.bound == 1

    # Second wave of pods arrives, then the API server dies.
    api.create_pod(make_pod("p2"))
    server.stop()

    # Cycles during the outage must not raise; watch errors are folded into
    # metrics. (Reflector backoff may suppress polls on some cycles; at least
    # one cycle must record an error.)
    for _ in range(3):
        sched.run_cycle()
        clock.advance(1.0)  # let the backoff window open virtually
    assert sched.metrics.snapshot().get("scheduler_watch_errors_total", 0) >= 1

    # Server returns on the same port with the (shared) cluster state.
    server2 = HttpApiServer(api, port=port).start()
    try:
        # Backoff grows toward backoff_max (30 s virtual); advancing a
        # virtual second per cycle guarantees a retry within the budget.
        deadline_cycles = 50
        bound = 0
        for _ in range(deadline_cycles):
            m = sched.run_cycle()
            bound += m.bound
            if bound:
                break
            clock.advance(1.0)
        assert bound == 1  # p2 got bound after recovery
        assert {p.spec.node_name for p in api.list_pods() if p.spec.node_name} == {"n1"}
    finally:
        server2.stop()


def test_bind_transport_error_requeues_single_pod():
    """A dropped connection mid-POST requeues that pod, not the cycle."""
    api = FakeApiServer()
    api.load(nodes=[make_node("n1", cpu=32, memory="64Gi")], pods=[make_pod("p1"), make_pod("p2")])

    class FlakyBindApi:
        def __init__(self, inner):
            self.inner = inner
            self.fail_next = 1

        def __getattr__(self, name):
            return getattr(self.inner, name)

        def create_binding(self, ns, name, target):
            if self.fail_next:
                self.fail_next -= 1
                raise BrokenPipeError("keep-alive dropped")
            return self.inner.create_binding(ns, name, target)

    sched = Scheduler(FlakyBindApi(api), NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 1  # the other pod still bound this cycle
    assert sched.metrics.snapshot().get("scheduler_requeues_total") == 1
    m2 = sched.run_cycle()
    assert m2.bound == 1  # requeued pod binds on retry


def test_device_failure_drops_upload_cache():
    """A device-runtime failure may orphan cached uploads (dead device
    session after a tunnel drop): the backend must forget them so recovery
    re-uploads instead of reusing corpses."""
    import jax

    from tpu_scheduler.errors import BackendUnavailable
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.testing import synth_cluster

    b = TpuBackend()
    packed = pack_snapshot(synth_cluster(n_nodes=10, n_pending=40, n_bound=5, seed=1))
    b.schedule(packed, DEFAULT_PROFILE)
    assert len(b._dev_cache) > 0

    orig = b._assign_once

    def boom(*a, **kw):
        raise jax.errors.JaxRuntimeError("device lost")

    b._assign_once = boom
    try:
        b.schedule(packed, DEFAULT_PROFILE)
        raise AssertionError("expected BackendUnavailable")
    except BackendUnavailable:
        pass
    assert len(b._dev_cache) == 0, "failure must drop cached uploads"
    b._assign_once = orig
    r = b.schedule(packed, DEFAULT_PROFILE)  # recovery re-uploads
    assert len(r.bindings) == 40


def test_cache_drop_covers_shards_and_dedups_finalizers():
    """Review repros: a session-wide failure must also drop SHARD backends'
    caches (dead buffers on siblings), and re-uploading the same live array
    after a drop must not stack a second finalizer."""
    import gc

    from tpu_scheduler.models.profiles import DEFAULT_PROFILE
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.testing import synth_cluster

    b = TpuBackend()
    shard = TpuBackend()
    b._shards[99] = shard
    packed = pack_snapshot(synth_cluster(n_nodes=10, n_pending=40, n_bound=5, seed=1))
    b.schedule(packed, DEFAULT_PROFILE)
    shard.schedule(packed, DEFAULT_PROFILE)
    assert len(shard._dev_cache) > 0
    b._drop_dev_cache()
    assert len(b._dev_cache) == 0 and len(shard._dev_cache) == 0

    # re-upload the SAME arrays after the drop: the drop detached the old
    # finalizers, so each cache entry carries exactly one LIVE finalizer
    # bound to a live array (the per-weakref design — no id-keyed registry
    # to stack or stale-block).
    b.schedule(packed, DEFAULT_PROFILE)
    n_entries = len(b._dev_cache)
    b._drop_dev_cache()
    b.schedule(packed, DEFAULT_PROFILE)
    assert len(b._dev_cache) == n_entries, "cache must rebuild to the same entry set"
    assert all(ent[2].alive and ent[0]() is not None for ent in b._dev_cache.values())
    del packed
    gc.collect()
    # Some arrays legitimately outlive the pack (module-level template
    # caches); the contract is: every REMAINING entry belongs to a live
    # array — dead arrays' finalizers evicted theirs.
    assert len(b._dev_cache) < n_entries, "dead arrays must leave the cache"
    assert all(ent[0]() is not None for ent in b._dev_cache.values())
