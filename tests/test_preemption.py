"""Priority preemption (runtime/controller.py; kube PostFilter — absent in
the reference): resource-starved high-priority pods evict strictly-lower-
priority victims with minimal disruption."""

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod

PREEMPT = DEFAULT_PROFILE.with_(preemption=True)


def _full_node(name="n1", cpu="4", memory="16Gi", **kw):
    return make_node(name, cpu=cpu, memory=memory, **kw)


def test_high_priority_pod_evicts_lowest_victims():
    api = FakeApiServer()
    api.load(
        nodes=[_full_node()],
        pods=[
            make_pod("low-a", cpu="2", memory="4Gi", node_name="n1", phase="Running", priority=1),
            make_pod("low-b", cpu="2", memory="4Gi", node_name="n1", phase="Running", priority=2),
            make_pod("vip", cpu="2", memory="4Gi", priority=10),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 1 and m.unschedulable == 0
    pods = {p.metadata.name: p for p in api.list_pods()}
    assert pods["vip"].spec.node_name == "n1"
    assert "low-a" not in pods  # the LOWEST priority victim went first
    assert "low-b" in pods  # one eviction sufficed — minimal disruption
    c = sched.metrics.snapshot()
    assert c["scheduler_preemptions_total"] == 1
    assert c["scheduler_preemption_victims_total"] == 1


def test_no_preemption_of_equal_or_higher_priority():
    api = FakeApiServer()
    api.load(
        nodes=[_full_node()],
        pods=[
            make_pod("same", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=5),
            make_pod("wanter", cpu="2", memory="4Gi", priority=5),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 1
    assert "default/same" not in [None]  # victim survives
    assert {p.metadata.name for p in api.list_pods()} == {"same", "wanter"}


def test_selector_mismatch_never_preempts():
    """Eviction cannot fix a non-resource predicate: a pod whose selector
    matches no node stays unschedulable even with victims available."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node(labels={"zone": "a"})],
        pods=[
            make_pod("victim", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=0),
            make_pod("misfit", cpu="1", memory="1Gi", priority=10, node_selector={"zone": "b"}),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 1
    assert {p.metadata.name for p in api.list_pods()} == {"victim", "misfit"}


def test_preemption_prefers_lowest_max_victim_priority():
    """Two feasible nodes: prefer the one whose required victims have the
    lower maximum priority (kube minimal-disruption)."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node("a"), _full_node("b")],
        pods=[
            make_pod("a-vic", cpu="4", memory="8Gi", node_name="a", phase="Running", priority=7),
            make_pod("b-vic", cpu="4", memory="8Gi", node_name="b", phase="Running", priority=2),
            make_pod("vip", cpu="2", memory="4Gi", priority=9),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    sched.run_cycle()
    pods = {p.metadata.name: p for p in api.list_pods()}
    assert pods["vip"].spec.node_name == "b"
    assert "b-vic" not in pods and "a-vic" in pods


def test_preemption_off_by_default():
    api = FakeApiServer()
    api.load(
        nodes=[_full_node()],
        pods=[
            make_pod("low", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=0),
            make_pod("vip", cpu="2", memory="4Gi", priority=10),
        ],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 1
    assert {p.metadata.name for p in api.list_pods()} == {"low", "vip"}


def test_multiple_preemptors_account_shared_capacity():
    """Two preemptors in one cycle: the second sees the first's placement
    and the freed pool honestly (no double-spend of evicted capacity)."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node(cpu="4", memory="16Gi")],
        pods=[
            make_pod("v1", cpu="2", memory="4Gi", node_name="n1", phase="Running", priority=0),
            make_pod("v2", cpu="2", memory="4Gi", node_name="n1", phase="Running", priority=0),
            make_pod("hi-a", cpu="2", memory="4Gi", priority=8),
            make_pod("hi-b", cpu="2", memory="4Gi", priority=9),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 2 and m.unschedulable == 0
    pods = {p.metadata.name: p for p in api.list_pods()}
    assert pods["hi-a"].spec.node_name == "n1" and pods["hi-b"].spec.node_name == "n1"
    assert "v1" not in pods and "v2" not in pods
    # capacity exact: 2 + 2 cores on a 4-core node, nothing oversubscribed
    assert sched.metrics.snapshot()["scheduler_preemption_victims_total"] == 2


def test_preemption_over_http_boundary(tmp_path):
    """The eviction DELETE flows through the REST boundary end-to-end."""
    from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient, RemoteApiAdapter

    api = FakeApiServer()
    server = HttpApiServer(api).start()
    try:
        api.load(
            nodes=[_full_node()],
            pods=[
                make_pod("low", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=0),
                make_pod("vip", cpu="2", memory="4Gi", priority=10),
            ],
        )
        adapter = RemoteApiAdapter(KubeApiClient(server.base_url))
        sched = Scheduler(adapter, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
        m = sched.run_cycle()
        assert m.bound == 1
        pods = {p.metadata.name: p for p in api.list_pods()}
        assert pods["vip"].spec.node_name == "n1" and "low" not in pods
    finally:
        server.stop()


def test_cli_preemption_flag(capsys):
    import json

    from tpu_scheduler.cli import main
    import tpu_scheduler.cli as cli_mod
    from tpu_scheduler.core.snapshot import ClusterSnapshot

    orig = cli_mod.synth_cluster

    def contended(**kw):
        nodes = [_full_node()]
        pods = [
            make_pod("low", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=0),
            make_pod("vip", cpu="2", memory="4Gi", priority=10),
        ]
        return ClusterSnapshot.build(nodes, pods)

    cli_mod.synth_cluster = contended
    try:
        rc = main(["--backend", "native", "--preemption", "--cycles", "2", "--requeue-seconds", "0"])
    finally:
        cli_mod.synth_cluster = orig
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["counters"]["scheduler_preemptions_total"] == 1


def test_preemption_sees_same_cycle_placements():
    """Regression (review repro): the pass must count capacity bound earlier
    in the SAME cycle — two 3-core equal-priority pods on a 4-core node must
    not both land (and a zero-eviction 'preemption' must not be counted)."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node(cpu="4", memory="16Gi")],
        pods=[
            make_pod("a", cpu="3", memory="4Gi", priority=5),
            make_pod("b", cpu="3", memory="4Gi", priority=5),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 1 and m.unschedulable == 1
    assert sched.metrics.snapshot().get("scheduler_preemptions_total", 0) == 0
    bound = [p for p in api.list_pods() if p.spec.node_name]
    assert len(bound) == 1  # 6/4 cores never happens


def test_preemption_sees_pipelined_dispatches():
    """Same invariant under --pipeline, where main-pass binds are only
    dispatched when the preemption pass runs."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node(cpu="4", memory="16Gi")],
        pods=[
            make_pod("a", cpu="3", memory="4Gi", priority=5),
            make_pod("b", cpu="3", memory="4Gi", priority=5),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=0.0, pipeline=True)
    sched.run_cycle()
    sched.run(until_settled=True, max_cycles=3)
    bound = [p for p in api.list_pods() if p.spec.node_name]
    assert len(bound) == 1
    assert sched.metrics.snapshot().get("scheduler_preemptions_total", 0) == 0


def test_preemptor_bind_failure_clears_backoff():
    """Victims already evicted + bind 500: the preemptor must stay eligible
    for the next cycle (approximated nominatedNodeName reservation)."""
    api = FakeApiServer()
    api.load(
        nodes=[_full_node()],
        pods=[
            make_pod("low", cpu="4", memory="8Gi", node_name="n1", phase="Running", priority=0),
            make_pod("vip", cpu="2", memory="4Gi", priority=10),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=PREEMPT, requeue_seconds=300.0)
    api.fail_next_bindings = 1  # the main pass never binds (node full); the preemption bind fails
    m = sched.run_cycle()
    assert m.bound == 0
    c = sched.metrics.snapshot()
    assert c.get("scheduler_preemption_bind_failures_total", 0) == 1
    assert "default/vip" not in sched.requeue_at  # eligible immediately
    m2 = sched.run_cycle()  # freed capacity is there; vip binds without more evictions
    assert m2.bound == 1
    pods = {p.metadata.name: p for p in api.list_pods()}
    assert pods["vip"].spec.node_name == "n1" and "low" not in pods
    assert c.get("scheduler_preemption_victims_total", 0) == 1
