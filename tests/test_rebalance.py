"""Background rebalancer (tpu_scheduler/rebalance): victim taxonomy,
packing solve (whole-node drains, topology preference, determinism), batch
selection + throttles, the unbind-then-cordon drain protocol end-to-end
(convergence, pressure release, background-thread mode, /debug surface),
the unbind CAS seam, and the pass-gated scenario family (defrag recovery
vs the rebalancer-off baseline, chaos composition, autoscaler what-if,
record→replay bit-identity on seeds {0, 1})."""

import json
import urllib.request

import numpy as np
import pytest

from tpu_scheduler.api.objects import ObjectReference, PodAntiAffinityTerm
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.rebalance import (
    MIGRATION_REASONS,
    REBALANCE_CORDON_LABEL,
    SKIP_REASONS,
    RebalanceConfig,
    Rebalancer,
    RebalanceSnapshot,
    packing_stats,
    solve_packing,
)
from tpu_scheduler.rebalance.planner import select_batch, throttle_reason
from tpu_scheduler.rebalance.snapshot import is_movable
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import ApiError, FakeApiServer
from tpu_scheduler.core.snapshot import ClusterSnapshot
from tpu_scheduler.testing import make_node, make_pod

from conftest import FakeClock


def _snap(nodes, pods):
    return ClusterSnapshot.build(nodes, pods)


# -- victim taxonomy ----------------------------------------------------------


def test_movable_taxonomy_pins_constrained_pods():
    assert is_movable(make_pod("plain", node_name="n1", phase="Running"))
    assert not is_movable(make_pod("g", node_name="n1", gang="team"))
    assert not is_movable(make_pod("sel", node_name="n1", node_selector={"zone": "a"}))
    assert not is_movable(
        make_pod("aa", node_name="n1", anti_affinity=[PodAntiAffinityTerm(topology_key="zone", match_labels={"a": "b"})])
    )
    assert not is_movable(make_pod("ext", node_name="n1", extended={"acme.com/gpu": 1}))
    assert not is_movable(make_pod("vetoed", node_name="n1"), victim_ok=lambda pf: False)


def test_movable_taxonomy_respects_pdbs():
    from tpu_scheduler.api.objects import PodDisruptionBudget, ObjectMeta

    pdb = PodDisruptionBudget(metadata=ObjectMeta(name="guard"), match_labels={"app": "db"}, min_available=1)
    protected = make_pod("db-0", node_name="n1", labels={"app": "db"})
    free = make_pod("web-0", node_name="n1", labels={"app": "web"})
    assert not is_movable(protected, pdbs=[pdb])
    assert is_movable(free, pdbs=[pdb])


# -- packing stats + solver ---------------------------------------------------


def test_packing_stats_exact_math():
    alloc = np.array([[8000, 100], [8000, 100], [8000, 100]], dtype=np.int64)
    used = np.array([[4000, 10], [2000, 10], [0, 0]], dtype=np.int64)
    s = packing_stats(alloc, used)
    assert s["occupied_nodes"] == 2 and s["empty_nodes"] == 1
    # Dominant axis: cpu 6000/16000 = 0.375 vs mem 20/200 = 0.1.
    assert s["efficiency"] == 0.375 and s["stranded_frac"] == 0.625
    empty = packing_stats(alloc, np.zeros_like(used))
    assert empty["efficiency"] == 1.0 and empty["occupied_nodes"] == 0


def test_solver_drains_whole_nodes_only_and_is_deterministic():
    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi") for i in range(4)]
    pods = [
        make_pod("a1", node_name="n0", cpu="1", memory="1Gi", phase="Running"),
        make_pod("a2", node_name="n1", cpu="1", memory="1Gi", phase="Running"),
        # n2 hosts a PINNED pod (gang): the node can never empty.
        make_pod("pin", node_name="n2", cpu="1", memory="1Gi", gang="team", phase="Running"),
        make_pod("b1", node_name="n2", cpu="1", memory="1Gi", phase="Running"),
        make_pod("big", node_name="n3", cpu="6", memory="4Gi", phase="Running"),
    ]
    rs = RebalanceSnapshot.build(_snap(nodes, pods))
    plan = solve_packing(rs)
    # n2 is pinned: no migration may name it as a source.
    assert all(m.src != "n2" for m in plan.migrations)
    # Every drained node's migrations move ALL of its movable mass.
    for src in plan.drained:
        moved = [m for m in plan.migrations if m.src == src]
        assert moved, src
    assert plan.after["occupied_nodes"] < plan.before["occupied_nodes"]
    plan2 = solve_packing(RebalanceSnapshot.build(_snap(nodes, pods)))
    assert plan.migrations == plan2.migrations and plan.drained == plan2.drained


def test_solver_respects_receiver_headroom_and_budget():
    nodes = [make_node("n0", cpu="4", memory="8Gi"), make_node("n1", cpu="4", memory="8Gi")]
    pods = [
        make_pod("x", node_name="n0", cpu="2", memory="1Gi", phase="Running"),
        make_pod("y", node_name="n1", cpu="3", memory="1Gi", phase="Running"),
    ]
    rs = RebalanceSnapshot.build(_snap(nodes, pods))
    # headroom 0.9 -> receiver n1 budget is 3.6 - 3 = 0.6 cores: x (2) cannot move.
    assert not solve_packing(rs, headroom=0.9).migrations
    # Full headroom: n1 can absorb x exactly (3 + 2 > 4 still fails)...
    assert not solve_packing(rs, headroom=1.0).migrations
    # ...but max_migrations=0 forbids everything outright on a drainable setup.
    pods2 = [
        make_pod("x", node_name="n0", cpu="1", memory="1Gi", phase="Running"),
        make_pod("y", node_name="n1", cpu="1", memory="1Gi", phase="Running"),
    ]
    rs2 = RebalanceSnapshot.build(_snap(nodes, pods2))
    assert solve_packing(rs2).migrations
    assert not solve_packing(rs2, max_migrations=0).migrations


def test_solver_topology_prefers_emptiest_rack_and_tags_rack_defrag():
    from tpu_scheduler.topology.model import TopologyModel

    labels = lambda r: {"topology.tpu-scheduler/rack": r}  # noqa: E731
    nodes = [
        make_node("a0", cpu="8", memory="32Gi", labels=labels("rack-a")),
        make_node("a1", cpu="8", memory="32Gi", labels=labels("rack-a")),
        make_node("b0", cpu="8", memory="32Gi", labels=labels("rack-b")),
    ]
    pods = [
        # rack-a: two busy nodes; rack-b: one nearly-empty node — the
        # emptiest COARSEST domain must drain first (freeing the rack).
        make_pod("a0-1", node_name="a0", cpu="4", memory="4Gi", phase="Running"),
        make_pod("a1-1", node_name="a1", cpu="4", memory="4Gi", phase="Running"),
        make_pod("b0-1", node_name="b0", cpu="1", memory="1Gi", phase="Running"),
    ]
    snap = _snap(nodes, pods)
    topo = TopologyModel.detect(nodes).compile(nodes)
    plan = solve_packing(RebalanceSnapshot.build(snap), topo=topo)
    assert plan.migrations and plan.migrations[0].src == "b0"
    assert plan.migrations[0].reason == "rack-defrag"  # rack-b empties whole
    assert plan.migrations[0].reason in MIGRATION_REASONS


# -- planner ------------------------------------------------------------------


def test_select_batch_takes_whole_node_groups():
    from tpu_scheduler.rebalance.solver import Migration, PackingPlan

    def mig(i, src):
        return Migration(pod_full=f"default/p{i}", src=src, dst="r", cpu=1, mem=1, reason="defrag-drain")

    plan = PackingPlan(
        migrations=(mig(0, "n0"), mig(1, "n0"), mig(2, "n0"), mig(3, "n1"), mig(4, "n1"), mig(5, "n2")),
        drained=("n0", "n1", "n2"),
        before={},
        after={},
    )
    groups = select_batch(plan, batch=4)
    # n0 whole (3) fits; n1 (2 more) would exceed 4 -> stops after n0.
    assert [g[0].src for g in groups] == ["n0"]
    # The FIRST group is taken even when it alone exceeds the batch.
    assert [g[0].src for g in select_batch(plan, batch=2)] == ["n0"]
    # The budget caps the total outright.
    assert select_batch(plan, batch=8, budget_left=2) == []


def test_throttle_reasons_precedence():
    cfg = RebalanceConfig(burn_limit=0.5, max_pending=4, max_migrations=10)
    assert throttle_reason("open", 0.0, 0, 0, 0, cfg) == "breaker-open"
    assert throttle_reason("closed", 0.9, 0, 0, 0, cfg) == "slo-burn"
    assert throttle_reason("closed", 0.0, 5, 0, 0, cfg) == "backlog"
    assert throttle_reason("closed", 0.0, 0, 3, 0, cfg) == "inflight"
    assert throttle_reason("closed", 0.0, 0, 0, 10, cfg) == "budget"
    assert throttle_reason("closed", 0.0, 0, 0, 0, cfg) is None
    for r in ("breaker-open", "slo-burn", "backlog", "inflight", "budget"):
        assert r in SKIP_REASONS


# -- executor unit ------------------------------------------------------------


def _frag_api(n_nodes=6, pods_per=2):
    api = FakeApiServer()
    for i in range(n_nodes):
        api.create_node(make_node(f"n{i}", cpu="8", memory="32Gi"))
    k = 0
    for i in range(n_nodes):
        for _ in range(pods_per):
            api.create_pod(make_pod(f"p{k}", node_name=f"n{i}", cpu="1", memory="1Gi", phase="Running"))
            k += 1
    return api


def test_executor_unbind_failure_aborts_group_without_cordon():
    api = _frag_api()
    snap = _snap(api.list_nodes(), api.list_pods())
    reb = Rebalancer(RebalanceConfig(every=1, batch=64))
    cordoned = []
    issued = reb.tick(
        snap,
        unbind=lambda pf, node: False,  # every deschedule fails
        cordon=lambda name: cordoned.append(name) or True,
    )
    assert issued == 0 and cordoned == []
    assert reb.skips.get("unbind-failed", 0) >= 1
    assert "unbind-failed" in SKIP_REASONS


def test_executor_victim_moved_abandons_stale_background_plan():
    """Background mode solves against an older snapshot: if the victims
    moved by the time the plan executes, the group is abandoned
    (victim-moved) — the next solve sees the truth."""
    import time as _time

    api = _frag_api(n_nodes=3)
    snap = _snap(api.list_nodes(), api.list_pods())
    reb = Rebalancer(RebalanceConfig(every=1, batch=64, background=True))
    calls = []
    try:
        # Tick 1 submits the solve request; no plan is ready yet.
        assert reb.tick(snap, unbind=lambda pf, n: calls.append(pf) or True, cordon=lambda n: True) == 0
        for _ in range(500):
            with reb._bg_lock:
                if reb._bg_plan is not None:
                    break
            _time.sleep(0.01)
        # The world moves under the finished plan: one extra pod bound per
        # node, so every planned group's victim set is stale.
        for i in range(3):
            api.create_pod(make_pod(f"late{i}", node_name=f"n{i}", cpu="1", memory="1Gi", phase="Running"))
        live = _snap(api.list_nodes(), api.list_pods())
        issued = reb.tick(live, unbind=lambda pf, n: calls.append(pf) or True, cordon=lambda n: True)
    finally:
        reb.close()
    assert issued == 0 and calls == []
    assert reb.skips.get("victim-moved", 0) >= 1
    assert "victim-moved" in SKIP_REASONS

def test_executor_reconcile_completions_and_vanished():
    api = _frag_api(n_nodes=2, pods_per=1)
    snap = _snap(api.list_nodes(), api.list_pods())
    reb = Rebalancer(RebalanceConfig(every=1, batch=8))
    unbound = []

    def unbind(pf, node):
        ns, _, name = pf.rpartition("/")
        api.unbind_pod(ns or "default", name, expect_node=node)
        unbound.append((pf, node))
        return True

    issued = reb.tick(snap, unbind=unbind, cordon=lambda n: True)
    assert issued >= 1 and len(reb.inflight) == issued
    # One pod re-binds, one vanishes: reconcile resolves both.
    pf0, node0 = unbound[0]
    ns, _, name0 = pf0.rpartition("/")
    api.create_binding(ns or "default", name0, ObjectReference(name="n1" if node0 == "n0" else "n0"))
    for pf, _n in unbound[1:]:
        ns1, _, n1 = pf.rpartition("/")
        api.delete_pod(ns1 or "default", n1)
    reb.reconcile(_snap(api.list_nodes(), api.list_pods()))
    assert reb.completed == 1
    assert reb.vanished == len(unbound) - 1
    assert not reb.inflight


# -- the unbind CAS seam ------------------------------------------------------


def test_unbind_pod_cas_and_watch_event():
    api = FakeApiServer()
    api.create_node(make_node("n0", cpu="8", memory="32Gi"))
    api.create_node(make_node("n1", cpu="8", memory="32Gi"))
    api.create_pod(make_pod("p", node_name="n0", phase="Running"))
    w = api.watch_pods(send_initial=False)
    with pytest.raises(ApiError) as e:
        api.unbind_pod("default", "p", expect_node="n1")  # CAS: wrong node
    assert e.value.code == 409
    with pytest.raises(ApiError):
        api.unbind_pod("default", "ghost")
    api.unbind_pod("default", "p", expect_node="n0")
    events = w.poll()
    assert [ev.type for ev in events] == ["MODIFIED"]
    assert events[0].object.spec.node_name is None
    assert events[0].object.status.phase == "Pending"
    with pytest.raises(ApiError) as e:
        api.unbind_pod("default", "p")  # already pending
    assert e.value.code == 409


# -- controller integration ---------------------------------------------------


def _drained_nodes(api):
    return sorted(
        n.name for n in api.list_nodes() if (n.metadata.labels or {}).get(REBALANCE_CORDON_LABEL)
    )


def test_controller_defrag_converges_and_audits_clean():
    api = _frag_api(n_nodes=8, pods_per=2)
    sched = Scheduler(
        api, NativeBackend(), clock=FakeClock(), requeue_seconds=0.0,
        rebalance=RebalanceConfig(every=2, batch=16),
    )
    for _ in range(24):
        sched.run_cycle()
    s = sched.rebalancer.stats()
    assert s["executed"] > 0 and s["completed"] == s["executed"]
    assert s["nodes_drained"] >= 5
    rs = RebalanceSnapshot.build(_snap(api.list_nodes(), api.list_pods()))
    stats = packing_stats(rs.alloc, rs.used)
    assert stats["occupied_nodes"] <= 3
    assert len(_drained_nodes(api)) == s["nodes_drained"]
    # Nothing pending, nothing lost: every migration re-placed.
    assert not [p for p in api.list_pods() if p.spec is None or not p.spec.node_name]
    # The delta ledger survived the churn exactly (migration = watch events).
    from tpu_scheduler.ops.pack import _alloc_and_used64

    st = sched.delta.state
    if st is not None:
        snap = _snap(api.list_nodes(), api.list_pods())
        alloc64, used64, _row = _alloc_and_used64(snap, st.alloc64.shape[0], None, st.res_vocab)
        assert (st.used64 == used64).all()


def test_pressure_release_uncordons_on_backlog():
    api = _frag_api(n_nodes=6, pods_per=1)
    sched = Scheduler(
        api, NativeBackend(), clock=FakeClock(), requeue_seconds=0.0,
        rebalance=RebalanceConfig(every=1, batch=16, max_pending=4),
    )
    for _ in range(12):
        sched.run_cycle()
    assert _drained_nodes(api), "setup: some nodes must have drained"
    # A demand wave larger than the throttle: the next tick must UNCORDON
    # every labeled node before standing down, and the wave then binds
    # (10 x 3-core pods need ~5 whole nodes — impossible while drained).
    for i in range(10):
        api.create_pod(make_pod(f"wave{i}", cpu="3", memory="4Gi"))
    for _ in range(4):
        sched.run_cycle()
    assert _drained_nodes(api) == []
    assert sched.rebalancer.pressure_releases >= 1
    assert sched.rebalancer.skips.get("backlog", 0) >= 1
    for _ in range(4):
        sched.run_cycle()
    assert not [p for p in api.list_pods() if p.spec is None or not p.spec.node_name]


def test_background_thread_mode_migrates():
    api = _frag_api(n_nodes=6, pods_per=2)
    sched = Scheduler(
        api, NativeBackend(), requeue_seconds=0.0,
        rebalance=RebalanceConfig(every=1, batch=16, background=True),
    )
    import time as _time

    try:
        for _ in range(40):
            sched.run_cycle()
            if sched.rebalancer.stats()["executed"]:
                break
            _time.sleep(0.01)  # let the worker finish a solve
        assert sched.rebalancer.stats()["executed"] > 0
    finally:
        sched.close()
    assert sched.rebalancer._bg_thread is None  # close() joined the worker


def test_debug_rebalance_route_and_snapshot():
    api = _frag_api(n_nodes=4, pods_per=1)
    sched = Scheduler(
        api, NativeBackend(), clock=FakeClock(), requeue_seconds=0.0,
        rebalance=RebalanceConfig(every=1, batch=8),
    )
    for _ in range(6):
        sched.run_cycle()
    snap = sched.rebalance_snapshot()
    assert snap["enabled"] and snap["solves"] >= 1
    assert snap["config"]["every"] == 1 and "drained_nodes" in snap
    from tpu_scheduler.runtime.http_api import HttpApiServer

    srv = HttpApiServer(api, rebalance=sched.rebalance_snapshot).start()
    try:
        with urllib.request.urlopen(f"{srv.base_url}/debug/rebalance") as r:
            body = json.loads(r.read())
        assert body["enabled"] and body["solves"] == snap["solves"]
        bare = HttpApiServer(api).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"{bare.base_url}/debug/rebalance")
            assert e.value.code == 404
        finally:
            bare.stop()
    finally:
        srv.stop()


def test_sharded_only_shard0_owner_rebalances():
    api = _frag_api(n_nodes=4, pods_per=1)
    sched = Scheduler(
        api, NativeBackend(), clock=FakeClock(), requeue_seconds=0.0, shards=2,
        identity="r0", lease_duration=30.0,
        rebalance=RebalanceConfig(every=1, batch=8),
    )
    for _ in range(6):
        sched.run_cycle()
    assert 0 in sched.shard_set.owned  # the only replica owns everything
    assert sched.rebalancer.stats()["solves"] >= 1


# -- scenario family (pass gates, baselines, chaos, replay) -------------------


def test_defrag_smoke_scenario_recovers_gate_and_baseline_fails():
    from tpu_scheduler.sim.harness import run_scenario

    for seed in (0, 1):
        card = run_scenario("defrag-smoke", seed=seed)
        r = card["rebalance"]
        assert card["pass"] and r["ok"], r
        assert r["packing_efficiency"] >= r["efficiency_gate"]
        assert 0 < r["migrations"] <= r["migration_budget"]
        assert r["orphaned_migrations"] == 0 and r["unbinds_while_open"] == 0
        assert card["pods"]["double_bound"] == 0 and card["pods"]["lost"] == 0
    off = run_scenario("defrag-smoke", seed=0, rebalance=False)
    assert not off["pass"] and not off["rebalance"]["ok"]
    assert off["rebalance"]["packing_efficiency"] < off["rebalance"]["efficiency_gate"]
    assert off["rebalance"]["migrations"] == 0


def test_defrag_smoke_record_replay_bit_identical(tmp_path):
    from tpu_scheduler.sim.harness import run_scenario

    p = str(tmp_path / "defrag.jsonl")
    live = run_scenario("defrag-smoke", seed=0, record=p)
    replayed = run_scenario("defrag-smoke", seed=0, replay=p)  # raises on mismatch
    assert replayed["fingerprint"] == live["fingerprint"]
    assert {**replayed, "mode": "live"} == live


def test_rebalance_under_chaos_zero_orphans_and_breaker_compose():
    from tpu_scheduler.sim.harness import run_scenario

    card = run_scenario("rebalance-under-chaos", seed=0)
    r = card["rebalance"]
    assert card["pass"], json.dumps(card["invariants"])[:500]
    assert r["orphaned_migrations"] == 0 and r["unbinds_while_open"] == 0
    assert card["pods"]["double_bound"] == 0
    assert card["availability"]["ok"]
    # The chaos actually composed: the breaker opened mid-defrag and the
    # rebalancer stood down for it (and survived injected unbind 500s).
    assert card["resilience"]["breaker_opened"] >= 1
    assert r["skips"].get("breaker-open", 0) >= 1
    assert r["migrations"] > 0 and r["completed"] == r["migrations"]


def test_autoscaler_whatif_recommends_node_adds():
    from tpu_scheduler.sim.harness import run_scenario

    card = run_scenario("autoscaler-backlog-whatif", seed=0)
    r = card["rebalance"]
    assert card["pass"] and r["ok"]
    w = r["whatif"]
    assert w is not None and w["pending_pods"] > 0
    assert w["nodes_needed"] >= 1  # the backlog needs real capacity
    assert r["skips"].get("backlog", 0) >= 1  # the throttle stood the tier down
    assert r["migrations"] == 0  # rebalancing never competed with the backlog


@pytest.mark.slow
def test_fragmentation_long_horizon_both_seeds():
    from tpu_scheduler.sim.harness import run_scenario

    for seed in (0, 1):
        card = run_scenario("fragmentation-long-horizon", seed=seed)
        r = card["rebalance"]
        assert card["pass"] and r["ok"], (seed, r)
        assert r["packing_efficiency"] >= r["efficiency_gate"]
        assert r["migrations"] <= r["migration_budget"]
    off = run_scenario("fragmentation-long-horizon", seed=0, rebalance=False)
    assert not off["pass"] and not off["rebalance"]["ok"]
