"""Multi-chip cycle on the virtual 8-device CPU mesh: the sharded (dp×tp)
auction must equal the single-device backends binding-for-binding."""

import numpy as np
import pytest

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.ops.pack import pack_snapshot
from tpu_scheduler.parallel.mesh import make_mesh, mesh_shape_for
from tpu_scheduler.parallel.sharded import ShardedBackend
from tpu_scheduler.testing import synth_cluster

from test_assign import check_validity


def test_mesh_shape_for():
    assert mesh_shape_for(8) == (4, 2)
    assert mesh_shape_for(8, tp=4) == (2, 4)
    assert mesh_shape_for(1) == (1, 1)
    assert mesh_shape_for(7) == (7, 1)
    with pytest.raises(ValueError):
        mesh_shape_for(8, tp=3)


@pytest.mark.parametrize("tp", [1, 2, 4])
@pytest.mark.parametrize("seed", [0, 1])
def test_sharded_parity_with_native(tp, seed):
    snap = synth_cluster(n_nodes=48, n_pending=280, n_bound=60, seed=seed)
    packed = pack_snapshot(snap, pod_block=64, node_block=16)
    native = NativeBackend().schedule(packed)
    sharded = ShardedBackend(make_mesh(tp=tp)).schedule(packed)
    assert (native.assigned == sharded.assigned).all(), np.flatnonzero(native.assigned != sharded.assigned)[:10]
    assert native.rounds == sharded.rounds
    check_validity(snap, packed, sharded)


def test_sharded_parity_under_contention():
    # Heavy contention: many auction rounds, cross-shard acceptance races.
    snap = synth_cluster(n_nodes=8, n_pending=500, seed=3, selector_fraction=0.4)
    packed = pack_snapshot(snap, pod_block=64, node_block=8)
    profile = DEFAULT_PROFILE.with_(max_rounds=256)
    native = NativeBackend().schedule(packed, profile)
    sharded = ShardedBackend(make_mesh(tp=2)).schedule(packed, profile)
    assert (native.assigned == sharded.assigned).all()
    check_validity(snap, packed, sharded)


def test_sharded_full_mesh_dp8():
    snap = synth_cluster(n_nodes=32, n_pending=333, seed=4)  # odd P: exercises padding
    packed = pack_snapshot(snap, pod_block=1, node_block=1)
    assert packed.padded_pods == 333  # deliberately unaligned to the mesh
    native = NativeBackend().schedule(packed)
    sharded = ShardedBackend(make_mesh(tp=1)).schedule(packed)
    assert (native.assigned == sharded.assigned).all()


def test_sharded_in_controller():
    from tpu_scheduler.runtime.controller import Scheduler
    from tpu_scheduler.runtime.fake_api import FakeApiServer

    api = FakeApiServer()
    snap = synth_cluster(n_nodes=16, n_pending=80, seed=6)
    api.load(snap.nodes, snap.pods)
    sched = Scheduler(api, ShardedBackend(make_mesh(tp=2)), fallback_backend=NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 80
    assert len(api.list_pods("status.phase=Pending")) == 0


def test_cli_tpu_sharded_end_to_end(capsys):
    """--backend=tpu-sharded schedules a synthetic cluster over the virtual
    8-device mesh from the CLI (VERDICT r2 item 7)."""
    import json

    from tpu_scheduler.cli import main

    rc = main(["--backend", "tpu-sharded", "--tp", "2", "--nodes", "16", "--pods", "64", "--cycles", "2"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["backend"] == "tpu-sharded"
    assert summary["bound_total"] == 64


def test_cli_tpu_sharded_constrained_cluster(capsys):
    """The sharded CLI path handles an anti-affinity cluster without host
    fallback (constraint tensors ride the mesh)."""
    import json

    from tpu_scheduler.cli import main
    import tpu_scheduler.cli as cli_mod
    import tpu_scheduler.testing as testing_mod

    orig = testing_mod.synth_cluster

    def constrained_synth(**kw):
        kw.setdefault("anti_affinity_fraction", 0.3)
        return orig(**kw)

    cli_mod.synth_cluster = constrained_synth
    try:
        rc = main(["--backend", "tpu-sharded", "--nodes", "12", "--pods", "36", "--cycles", "3"])
    finally:
        cli_mod.synth_cluster = orig
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    summary = json.loads(out[-1])
    assert summary["counters"].get("scheduler_constraint_tensor_cycles_total", 0) >= 1
    assert summary["counters"].get("scheduler_constraint_host_fallbacks_total", 0) == 0
    assert summary["bound_total"] > 0


@pytest.mark.parametrize("tp", [1, 2])
def test_sharded_pallas_parity(tp):
    """VERDICT r3 #3: the fused choose kernel inside shard_map (interpret
    mode on the CPU mesh) must equal the jnp shard program and the native
    oracle binding-for-binding — the jitter hash sees GLOBAL node indices
    via the kernel's node_offset, so tp slicing must not shift choices."""
    snap = synth_cluster(n_nodes=48, n_pending=280, n_bound=60, seed=2)
    packed = pack_snapshot(snap, pod_block=64, node_block=16)
    native = NativeBackend().schedule(packed)
    sharded = ShardedBackend(make_mesh(tp=tp), use_pallas=True, pallas_interpret=True).schedule(packed)
    assert (native.assigned == sharded.assigned).all(), np.flatnonzero(native.assigned != sharded.assigned)[:10]
    assert native.rounds == sharded.rounds
    check_validity(snap, packed, sharded)


def test_sharded_pallas_constrained_parity():
    """Constrained cycles through the sharded pallas path: blocked/penalty
    masks slice per tp shard and feed the constrained kernel variant."""
    snap = synth_cluster(
        n_nodes=32, n_pending=120, n_bound=64, seed=5,
        anti_affinity_fraction=0.2, spread_fraction=0.2, schedule_anyway_fraction=0.2,
        pod_affinity_fraction=0.15, preferred_pod_affinity_fraction=0.2,
    )
    from dataclasses import replace

    from tpu_scheduler.ops.constraints import pack_constraints

    packed = pack_snapshot(snap, pod_block=32, node_block=16)
    cons = pack_constraints(snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes)
    assert cons is not None
    packed = replace(packed, constraints=cons)
    native = NativeBackend().schedule(packed)
    sharded = ShardedBackend(make_mesh(tp=2), use_pallas=True, pallas_interpret=True).schedule(packed)
    # Bit-parity with the native oracle is the contract; check_validity's
    # "unscheduled => infeasible" clause doesn't apply to constrained
    # clusters (constraints legitimately defer resource-feasible pods —
    # the order-witness replay in test_constraints_tensor covers validity).
    assert (native.assigned == sharded.assigned).all(), np.flatnonzero(native.assigned != sharded.assigned)[:10]
    assert native.rounds == sharded.rounds


def test_sharded_parity_fuzz_large_non_dividing_shapes():
    """VERDICT r4 #6: shard-boundary bugs (tile-edge tie-breaks, gather
    ordering) only appear at larger P/N and UNEVEN shards.  The shared
    scenario (testing.uneven_shard_scenario) keeps the padded axes at
    1003 x 257 — odd/prime, indivisible by every dp/tp here, so the shard
    padding paths genuinely run — both mesh factorizations, constrained
    included, vs the single-device oracle."""
    from tpu_scheduler.testing import uneven_shard_scenario

    packed, cpacked = uneven_shard_scenario()
    oracle_plain = NativeBackend().schedule(packed)
    oracle_cons = NativeBackend().schedule(cpacked)
    for tp in (2, 4):
        sb = ShardedBackend(tp=tp)
        rs = sb.schedule(packed)
        assert (rs.assigned == oracle_plain.assigned).all(), f"plain tp={tp} diverged at 1003x257"
        rc = sb.schedule(cpacked)
        assert (rc.assigned == oracle_cons.assigned).all(), f"constrained tp={tp} diverged at 1003x257"
    assert len(oracle_cons.bindings) > 800  # the shape actually schedules at scale
