"""Checkpoint/resume tests (runtime/checkpoint.py): requeue backoffs and
metric counters survive a scheduler restart; the packed node-tensor cache
seeds the incremental pack path; stale checkpoints degrade to a full repack,
never a wrong decision."""

import numpy as np
import pytest

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.checkpoint import restore_scheduler, save_scheduler
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def build(api=None, clock=None):
    api = api or FakeApiServer()
    return Scheduler(api, NativeBackend(), policy="batch", clock=clock or FakeClock())


def test_restore_missing_checkpoint_is_noop(tmp_path):
    sched = build()
    assert restore_scheduler(sched, str(tmp_path / "nope")) is False
    assert sched.requeue_at == {}


def test_requeue_backoffs_survive_restart(tmp_path):
    api = FakeApiServer()
    # One node with no capacity -> the pod requeues (no-node-found).
    api.load(nodes=[make_node("n1", cpu="0", memory="0")], pods=[make_pod("stuck", cpu="1", memory="1Gi")])
    clock = FakeClock(100.0)
    sched = build(api, clock)
    sched.run_cycle()
    assert "default/stuck" in sched.requeue_at
    deadline = sched.requeue_at["default/stuck"]
    assert deadline == pytest.approx(100.0 + sched.requeue_seconds)

    save_scheduler(sched, str(tmp_path))

    # Restarted process: new scheduler, new monotonic clock origin.
    clock2 = FakeClock(5.0)
    sched2 = build(api, clock2)
    assert restore_scheduler(sched2, str(tmp_path)) is True
    # Remaining time is preserved relative to the new clock.
    assert sched2.requeue_at["default/stuck"] == pytest.approx(5.0 + sched.requeue_seconds)
    # Still backing off: the cycle must skip it.
    m = sched2.run_cycle()
    assert m.pending == 0

    # After the backoff elapses it schedules again (and still fails -> requeued).
    clock2.t += sched2.requeue_seconds + 1
    m = sched2.run_cycle()
    assert m.pending == 1 and m.unschedulable == 1


def test_counters_survive_restart(tmp_path):
    api = FakeApiServer()
    api.load(nodes=[make_node("n1", cpu="8", memory="32Gi")], pods=[make_pod(f"p{i}") for i in range(3)])
    sched = build(api)
    sched.run_cycle()
    assert sched.metrics.counters["scheduler_bindings_total"] == 3
    save_scheduler(sched, str(tmp_path))

    sched2 = build(api)
    restore_scheduler(sched2, str(tmp_path))
    assert sched2.metrics.counters["scheduler_bindings_total"] == 3
    assert sched2._cycle_count == sched._cycle_count


def test_packed_cache_seeds_incremental_pack(tmp_path):
    api = FakeApiServer()
    api.load(
        nodes=[make_node(f"n{i}", cpu="8", memory="32Gi") for i in range(4)],
        pods=[make_pod(f"p{i}") for i in range(6)],
    )
    sched = build(api)
    sched.run_cycle()
    assert sched.metrics.counters.get("scheduler_full_packs_total", 0) == 1
    save_scheduler(sched, str(tmp_path))

    sched2 = build(api)
    restore_scheduler(sched2, str(tmp_path))
    assert sched2._packed is not None
    np.testing.assert_array_equal(sched2._packed.node_alloc, sched._packed.node_alloc)
    # More work arrives; the restarted scheduler takes the incremental path.
    for i in range(3):
        api.create_pod(make_pod(f"late-{i}"))
    m = sched2.run_cycle()
    assert m.bound == 3
    assert sched2.metrics.counters.get("scheduler_incremental_packs_total", 0) >= 1
    assert sched2.metrics.counters["scheduler_full_packs_total"] == 1  # restored count, no new full pack


def test_stale_checkpoint_falls_back_to_full_pack(tmp_path):
    api = FakeApiServer()
    api.load(nodes=[make_node("n1", cpu="8", memory="32Gi")], pods=[make_pod("p0")])
    sched = build(api)
    sched.run_cycle()
    save_scheduler(sched, str(tmp_path))

    # The cluster changed while we were down: different node set.
    api2 = FakeApiServer()
    api2.load(
        nodes=[make_node("m1", cpu="8", memory="32Gi"), make_node("m2", cpu="8", memory="32Gi")],
        pods=[make_pod("q0"), make_pod("q1")],
    )
    sched2 = build(api2)
    restore_scheduler(sched2, str(tmp_path))
    m = sched2.run_cycle()
    assert m.bound == 2  # correct scheduling despite the stale cache
    # restored full-pack counter was 1; the stale cache forces one more
    assert sched2.metrics.counters["scheduler_full_packs_total"] == 2


def test_v2_checkpoint_migrates_into_sharded_controller(tmp_path):
    """v2 -> v3 migration: a flat-layout v2 checkpoint restores cleanly into
    a SHARDED controller — attempt counters preserved, deadlines re-based on
    the new clock, and a subsequent save writes the v3 layout with every pod
    grouped under its stable-hash shard."""
    import json
    import os

    from tpu_scheduler.runtime.shards import shard_for_name

    v2_state = {
        "version": 2,
        "cycle_count": 7,
        "counters": {"scheduler_bindings_total": 3},
        "requeue_remaining": {"default/a": 12.0, "default/b": 0.5, "default/g1-0": 3.0},
        "requeue_meta": {"default/a": ["no-node", 4], "default/b": ["api-error", 2], "default/g1-0": ["gang", 1]},
        "noexecute_elapsed": [],
        "pdb_peaks": {},
        "pdb_disruptions": {},
        "node_sig": None,
    }
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(os.path.join(str(tmp_path), "state.json"), "w") as f:
        json.dump(v2_state, f)

    api = FakeApiServer()
    api.load(nodes=[make_node("n1", cpu="8", memory="32Gi")], pods=[])
    clock = FakeClock(50.0)
    sched = Scheduler(api, NativeBackend(), clock=clock, shards=4, identity="r1", lease_duration=6.0)
    assert restore_scheduler(sched, str(tmp_path)) is True
    # Attempt counters preserved; deadlines re-based on the new clock.
    assert sched.requeue_at.attempts("default/a") == 4
    assert sched.requeue_at.meta()["default/b"] == ("api-error", 2)
    assert sched.requeue_at["default/a"] == pytest.approx(62.0)
    assert sched._cycle_count == 7
    # The sharded controller schedules by live stable-hash assignment; a
    # save from here writes the v3 layout with each pod in its hash shard.
    save_scheduler(sched, str(tmp_path))
    with open(os.path.join(str(tmp_path), "state.json")) as f:
        v3 = json.load(f)
    assert v3["version"] == 5 and v3["shard_count"] == 4
    for pf in ("default/a", "default/b", "default/g1-0"):
        assert pf in v3["shards"][str(shard_for_name(pf, 4))]["requeue"]


def test_version_mismatch_raises(tmp_path):
    sched = build()
    save_scheduler(sched, str(tmp_path))
    import json
    import os

    p = os.path.join(str(tmp_path), "state.json")
    with open(p) as f:
        state = json.load(f)
    state["version"] = 999
    with open(p, "w") as f:
        json.dump(state, f)
    with pytest.raises(ValueError):
        restore_scheduler(build(), str(tmp_path))


def test_reordered_node_cache_falls_back_not_crash(tmp_path):
    """A restored cache whose node ORDER differs from the live reflector's
    (sorted signature matches, order-sensitive pack doesn't) must degrade to
    a full repack, not crash every cycle (review finding)."""
    api = FakeApiServer()
    api.load(nodes=[make_node("a", cpu="8", memory="32Gi"), make_node("c", cpu="8", memory="32Gi")], pods=[])
    sched = build(api)
    sched.run_cycle()
    api.create_node(make_node("b", cpu="8", memory="32Gi"))
    api.create_pod(make_pod("p0"))
    sched.run_cycle()  # reflector order now (a, c, b)
    save_scheduler(sched, str(tmp_path))

    # Restarted process relists in name order (a, b, c): same sorted
    # signature, different order.
    api2 = FakeApiServer()
    api2.load(
        nodes=[
            make_node("a", cpu="8", memory="32Gi"),
            make_node("b", cpu="8", memory="32Gi"),
            make_node("c", cpu="8", memory="32Gi"),
        ],
        pods=[make_pod("q0")],
    )
    # Give the restarted store identical (name, rv) pairs so the sorted
    # signature matches the checkpoint's.
    by_name = {n.name: n for n in api2.list_nodes()}
    for old in api.list_nodes():
        by_name[old.name].metadata.resource_version = old.metadata.resource_version
    sched2 = build(api2)
    restore_scheduler(sched2, str(tmp_path))
    m = sched2.run_cycle()  # must not raise
    assert m.bound == 1
