"""Taints/tolerations + node-cordon tests: scalar semantics (the oracle),
tensorization (pack bitmaps), batched-backend parity on tainted clusters, and
the control loop honoring both predicates end-to-end."""

import numpy as np

from tpu_scheduler.api.objects import Node, Pod, Taint, Toleration, node_to_dict, pod_to_dict
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.core.predicates import (
    InvalidNodeReason,
    check_node_validity,
    node_schedulable,
    taints_tolerated,
)
from tpu_scheduler.core.snapshot import ClusterSnapshot
from tpu_scheduler.ops.pack import build_taint_vocab, pack_snapshot
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod, synth_cluster


def snap(nodes, pods):
    return ClusterSnapshot.build(nodes, pods)


# --- toleration matching semantics ------------------------------------------


def test_toleration_equal_matches():
    t = Toleration(key="pool", operator="Equal", value="gpu", effect="NoSchedule")
    assert t.tolerates(Taint(key="pool", value="gpu", effect="NoSchedule"))
    assert not t.tolerates(Taint(key="pool", value="cpu", effect="NoSchedule"))
    assert not t.tolerates(Taint(key="other", value="gpu", effect="NoSchedule"))


def test_toleration_exists_ignores_value():
    t = Toleration(key="pool", operator="Exists")
    assert t.tolerates(Taint(key="pool", value="anything", effect="NoSchedule"))
    assert t.tolerates(Taint(key="pool", value="else", effect="NoExecute"))  # empty effect matches any
    assert not t.tolerates(Taint(key="other", value="x", effect="NoSchedule"))


def test_toleration_empty_key_exists_tolerates_everything():
    t = Toleration(operator="Exists")
    assert t.tolerates(Taint(key="anything", value="v", effect="NoExecute"))


def test_toleration_effect_scoping():
    t = Toleration(key="k", operator="Exists", effect="NoSchedule")
    assert t.tolerates(Taint(key="k", effect="NoSchedule"))
    assert not t.tolerates(Taint(key="k", effect="NoExecute"))


def test_empty_key_equal_operator_matches_nothing():
    t = Toleration(operator="Equal")  # empty key with Equal: not a tolerate-all
    assert not t.tolerates(Taint(key="k", value="", effect="NoSchedule"))


# --- scalar predicates -------------------------------------------------------


def test_taints_tolerated_predicate():
    node = make_node("n1", taints=[Taint(key="pool", value="gpu", effect="NoSchedule")])
    plain = make_pod("plain")
    tolerant = make_pod("tol", tolerations=[Toleration(key="pool", operator="Equal", value="gpu", effect="NoSchedule")])
    assert not taints_tolerated(plain, node)
    assert taints_tolerated(tolerant, node)


def test_prefer_no_schedule_is_soft():
    node = make_node("n1", taints=[Taint(key="pool", value="gpu", effect="PreferNoSchedule")])
    assert taints_tolerated(make_pod("plain"), node)


def test_node_schedulable_cordon():
    assert node_schedulable(make_pod("p"), make_node("n1"))
    assert not node_schedulable(make_pod("p"), make_node("n2", unschedulable=True))


def test_chain_reports_taint_and_cordon_reasons():
    tainted = make_node("n1", taints=[Taint(key="k", effect="NoSchedule")])
    cordoned = make_node("n2", unschedulable=True)
    pod = make_pod("p")
    s = snap([tainted, cordoned], [pod])
    assert check_node_validity(pod, tainted, s) is InvalidNodeReason.TAINT_NOT_TOLERATED
    assert check_node_validity(pod, cordoned, s) is InvalidNodeReason.NODE_UNSCHEDULABLE


# --- serialization -----------------------------------------------------------


def test_taint_toleration_roundtrip():
    node = make_node("n1", taints=[Taint(key="pool", value="gpu", effect="NoExecute")], unschedulable=True)
    assert Node.from_dict(node_to_dict(node)) == node
    pod = make_pod("p", tolerations=[Toleration(key="pool", operator="Exists", effect="NoSchedule")])
    assert Pod.from_dict(pod_to_dict(pod)) == pod


# --- tensorization -----------------------------------------------------------


def test_taint_vocab_hard_effects_only():
    nodes = [
        make_node("n1", taints=[Taint(key="a", value="1", effect="NoSchedule")]),
        make_node("n2", taints=[Taint(key="b", value="2", effect="PreferNoSchedule")]),
        make_node("n3", taints=[Taint(key="c", value="3", effect="NoExecute")]),
    ]
    vocab = build_taint_vocab(nodes)
    assert ("a", "1", "NoSchedule") in vocab
    assert ("c", "3", "NoExecute") in vocab
    assert all(e != "PreferNoSchedule" for (_, _, e) in vocab)


def test_pack_taint_bitmaps_match_scalar_oracle():
    s = synth_cluster(n_nodes=20, n_pending=40, n_bound=10, seed=3, tainted_fraction=0.5, cordoned_fraction=0.2)
    packed = pack_snapshot(s, pod_block=8, node_block=8)
    pending = s.pending_pods()
    for i, pod in enumerate(pending):
        for j, node in enumerate(s.nodes):
            # tensor verdict: tolerable iff no untolerated taint lands on node
            untol = float(packed.pod_ntol[i] @ packed.node_taints[j])
            assert (untol == 0) == taints_tolerated(pod, node), (pod.name, node.name)
            assert bool(packed.node_valid[j]) == node_schedulable(pod, node), node.name


def test_cordoned_node_invalid_in_pack():
    nodes = [make_node("n1"), make_node("n2", unschedulable=True)]
    s = snap(nodes, [make_pod("p")])
    packed = pack_snapshot(s, pod_block=8, node_block=8)
    assert bool(packed.node_valid[0]) and not bool(packed.node_valid[1])


# --- batched parity + end-to-end --------------------------------------------


def test_native_backend_respects_taints():
    nodes = [
        make_node("gpu-node", cpu="8", memory="32Gi", taints=[Taint(key="pool", value="gpu", effect="NoSchedule")]),
        make_node("cpu-node", cpu="8", memory="32Gi"),
    ]
    pods = [make_pod(f"plain-{i}") for i in range(4)] + [
        make_pod(
            f"gpu-{i}",
            tolerations=[Toleration(key="pool", operator="Equal", value="gpu", effect="NoSchedule")],
        )
        for i in range(2)
    ]
    s = snap(nodes, pods)
    packed = pack_snapshot(s, pod_block=8, node_block=8)
    result = NativeBackend().schedule(packed)
    by_pod = dict(result.bindings)
    for i in range(4):
        assert by_pod[f"default/plain-{i}"] == "cpu-node"  # taint keeps them off gpu-node


def test_backend_parity_tainted_cluster():
    s = synth_cluster(n_nodes=30, n_pending=120, n_bound=20, seed=11, tainted_fraction=0.4, cordoned_fraction=0.15)
    packed = pack_snapshot(s, pod_block=32, node_block=8)
    from tpu_scheduler.backends.tpu import TpuBackend

    rn = NativeBackend().schedule(packed)
    rt = TpuBackend().schedule(packed)
    np.testing.assert_array_equal(rn.assigned, rt.assigned)


def test_scheduler_never_binds_to_cordoned_or_untolerated():
    nodes = [
        make_node("ok", cpu="16", memory="64Gi"),
        make_node("cordoned", cpu="16", memory="64Gi", unschedulable=True),
        make_node("tainted", cpu="16", memory="64Gi", taints=[Taint(key="dedicated", effect="NoSchedule")]),
    ]
    pods = [make_pod(f"p{i}", cpu="250m", memory="512Mi") for i in range(10)]
    api = FakeApiServer()
    api.load(nodes=nodes, pods=pods)
    sched = Scheduler(api, NativeBackend(), policy="batch")
    m = sched.run_cycle()
    assert m.bound == 10
    for p in api.list_pods():
        assert p.spec.node_name == "ok"


def test_sample_policy_respects_taints():
    import random

    nodes = [
        make_node("ok", cpu="16", memory="64Gi"),
        make_node("tainted", cpu="16", memory="64Gi", taints=[Taint(key="dedicated", effect="NoExecute")]),
    ]
    pods = [make_pod(f"p{i}", cpu="250m", memory="512Mi") for i in range(8)]
    api = FakeApiServer()
    api.load(nodes=nodes, pods=pods)
    sched = Scheduler(api, NativeBackend(), policy="sample", attempts=50, rng=random.Random(4))
    sched.run_cycle()
    for p in api.list_pods():
        if p.spec.node_name is not None:
            assert p.spec.node_name == "ok"
