"""Wire-format e2e: realistic kube manifests (dicts in the k8s JSON shape)
flow through Pod.from_dict / Node.from_dict into the controller and come out
as correct placements — the integration test of the whole API surface:
affinity (node + pod, hard + soft), tolerations with tolerationSeconds,
spread, priority, gang labels."""

from tpu_scheduler.api.objects import Node, Pod, PodDisruptionBudget
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer


def _node(name, zone, cpu="8", taints=None):
    d = {
        "kind": "Node",
        "metadata": {"name": name, "labels": {"zone": zone, "name": name}},
        "status": {"allocatable": {"cpu": cpu, "memory": "32Gi"}},
    }
    if taints:
        d["spec"] = {"taints": taints}
    return Node.from_dict(d)


def _pod(name, labels=None, spec_extra=None, cpu="500m"):
    spec = {
        "containers": [{"name": "main", "resources": {"requests": {"cpu": cpu, "memory": "256Mi"}}}],
        **(spec_extra or {}),
    }
    return Pod.from_dict(
        {"kind": "Pod", "metadata": {"name": name, "namespace": "default", "labels": labels or {}}, "spec": spec}
    )


def test_manifest_cluster_schedules_correctly():
    nodes = [
        _node("a1", "z1"),
        _node("a2", "z1"),
        _node("b1", "z2"),
        _node("c1", "z3", taints=[{"key": "maint", "value": "drain", "effect": "NoSchedule"}]),
    ]
    cache = _pod("cache-0", labels={"app": "cache"})
    # required co-location with cache over zone + a soft anti-preference
    # against noisy, node-affinity excluding z3, toleration for the taint
    web = _pod(
        "web-0",
        labels={"app": "web"},
        spec_extra={
            "priority": 5,
            "affinity": {
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {"topologyKey": "zone", "labelSelector": {"matchLabels": {"app": "cache"}}}
                    ]
                },
                "podAntiAffinity": {
                    "preferredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "weight": 100,
                            "podAffinityTerm": {
                                "topologyKey": "zone",
                                "labelSelector": {"matchLabels": {"app": "noisy"}},
                            },
                        }
                    ]
                },
            },
            "tolerations": [{"key": "maint", "operator": "Equal", "value": "drain", "effect": "NoSchedule"}],
        },
    )
    # hostname anti-affinity pair: must land on distinct nodes
    db = [
        _pod(
            f"db-{i}",
            labels={"app": "db"},
            spec_extra={
                "affinity": {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"topologyKey": "name", "labelSelector": {"matchLabels": {"app": "db"}}}
                        ]
                    }
                }
            },
        )
        for i in range(2)
    ]
    api = FakeApiServer()
    api.load(nodes=nodes, pods=[cache] + db + [web])
    api.create_pdb(
        PodDisruptionBudget.from_dict(
            {"metadata": {"name": "db", "namespace": "default"}, "spec": {"selector": {"matchLabels": {"app": "db"}}, "minAvailable": 2}}
        )
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 4, f"all four manifest pods must place ({m.unschedulable} unschedulable)"
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods()}
    zone = {"a1": "z1", "a2": "z1", "b1": "z2", "c1": "z3"}
    assert zone[placed["web-0"]] == zone[placed["cache-0"]], "required podAffinity violated"
    assert placed["db-0"] != placed["db-1"], "hostname anti-affinity violated"


def test_manifest_toleration_seconds_lifecycle():
    now = [0.0]
    api = FakeApiServer()
    api.load(
        nodes=[_node("a1", "z1", taints=[{"key": "maint", "value": "x", "effect": "NoExecute"}]), _node("b1", "z2")],
        pods=[
            _pod(
                "graced",
                spec_extra={
                    "nodeName": "a1",
                    "tolerations": [
                        {"key": "maint", "operator": "Equal", "value": "x", "effect": "NoExecute", "tolerationSeconds": 120}
                    ],
                },
            )
        ],
    )
    # mark it running (from_dict defaults to Pending; nodeName set = bound)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, clock=lambda: now[0])
    sched.run_cycle()
    assert "graced" in {p.metadata.name for p in api.list_pods()}
    now[0] = 121.0
    sched.run_cycle()
    assert "graced" not in {p.metadata.name for p in api.list_pods()}
