"""Regression tests for the second code-review round: bound-but-Pending
capacity accounting, negative-priority jitter-rank parity, requeue cleanup,
shim whitespace, synth/CLI guards."""

import numpy as np

from tpu_scheduler import ClusterSnapshot
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.backends.tpu import TpuBackend
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.ops.pack import pack_snapshot
from tpu_scheduler.parallel.mesh import make_mesh
from tpu_scheduler.parallel.sharded import ShardedBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod, synth_cluster


def test_bound_but_pending_pod_counts_capacity():
    # Pod bound to the node but phase still Pending (kubelet lag) must consume
    # capacity in the cycle snapshot — previously it was dropped and the node
    # oversubscribed (3 + 2 > 4 cores).
    api = FakeApiServer()
    api.create_node(make_node("n1", cpu="4", memory="32Gi"))
    api.create_pod(make_pod("bp", cpu="3", memory="1Gi", node_name="n1", phase="Pending"))
    api.create_pod(make_pod("p", cpu="2", memory="1Gi"))
    sched = Scheduler(api, NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 1  # p cannot fit next to bp


def make_negative_priority_packed():
    # padded_pods=384 with block 256 forces jax-side block padding; a
    # negative-priority pod must land at the same rank in every backend.
    snap = synth_cluster(n_nodes=16, n_pending=299, seed=13)
    pods = list(snap.pods) + [make_pod("negprio", cpu="500m", memory="1Gi", priority=-5)]
    snap = ClusterSnapshot.build(snap.nodes, pods)
    return snap, pack_snapshot(snap, pod_block=128)


def test_negative_priority_parity_tpu():
    snap, packed = make_negative_priority_packed()
    profile = DEFAULT_PROFILE.with_(pod_block=256)
    native = NativeBackend().schedule(packed, profile)
    tpu = TpuBackend().schedule(packed, profile)
    assert (native.assigned == tpu.assigned).all(), np.flatnonzero(native.assigned != tpu.assigned)[:10]


def test_negative_priority_parity_sharded():
    snap, packed = make_negative_priority_packed()
    native = NativeBackend().schedule(packed)
    sharded = ShardedBackend(make_mesh(tp=2)).schedule(packed)
    assert (native.assigned == sharded.assigned).all()


from conftest import FakeClock


def test_requeue_cleared_when_pod_deleted():
    clock = FakeClock()
    api = FakeApiServer()
    api.create_node(make_node("tiny", cpu="1", memory="1Gi"))
    api.create_pod(make_pod("huge", cpu="64", memory="256Gi"))
    sched = Scheduler(api, NativeBackend(), clock=clock)
    sched.run_cycle()
    assert "default/huge" in sched.requeue_at
    # Delete and recreate with a feasible spec under the same name: the new
    # pod must NOT inherit the old backoff.
    api.delete_pod("default", "huge")
    sched.run_cycle()  # prunes the stale entry
    assert "default/huge" not in sched.requeue_at
    api.create_pod(make_pod("huge", cpu="500m", memory="512Mi"))
    clock.t = 10.0  # well inside the old 300 s window
    m = sched.run_cycle()
    assert m.bound == 1


def test_requeue_cleared_on_successful_bind():
    clock = FakeClock()
    api = FakeApiServer()
    api.create_node(make_node("n1", cpu="8", memory="32Gi"))
    api.create_pod(make_pod("p1", cpu="1", memory="1Gi"))
    api.fail_next_bindings = 1
    sched = Scheduler(api, NativeBackend(), clock=clock)
    sched.run_cycle()
    assert "default/p1" in sched.requeue_at
    clock.t = 301.0
    m = sched.run_cycle()
    assert m.bound == 1
    assert sched.requeue_at == {}


def test_shim_accepts_whitespace_like_python():
    from conftest import ensure_native_shim
    from tpu_scheduler.api.quantity import memory_to_bytes
    from tpu_scheduler.ops import native_ext

    ensure_native_shim()
    for s in ["1Gi ", " 1Gi", "\t2Ki\n", " 500 "]:
        assert native_ext.batch_parse([s], native_ext.MODE_MEM_BYTES)[0] == memory_to_bytes(s)


def test_synth_cluster_zero_nodes_with_bound():
    snap = synth_cluster(n_nodes=0, n_pending=3, n_bound=5)
    assert len(snap.nodes) == 0
    assert len(snap.pending_pods()) == 3
