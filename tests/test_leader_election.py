"""Lease-based leader election (SURVEY.md §5 — the reference has none):
only the lease holder schedules; standbys keep warm caches and take over
within the lease TTL of the leader vanishing, or immediately on clean
hand-off."""

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _cluster(api, pods=6):
    api.load(
        nodes=[make_node(f"n{i}", cpu="8", memory="32Gi") for i in range(2)],
        pods=[make_pod(f"p{i}") for i in range(pods)],
    )


def test_only_leader_schedules():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock)
    m1 = s1.run_cycle()  # acquires the lease, schedules
    m2 = s2.run_cycle()  # standby: lease held
    assert s1.is_leader and not s2.is_leader
    assert m1.bound == 6 and m2.bound == 0
    assert s1.metrics.snapshot()["scheduler_leadership_acquisitions_total"] == 1
    assert "scheduler_leadership_acquisitions_total" not in s2.metrics.snapshot()


def test_standby_takes_over_after_lease_expiry():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock, lease_duration=15.0)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock, lease_duration=15.0)
    s1.run_cycle()
    assert s1.is_leader
    # Leader dies silently (stops renewing); lease not yet expired.
    clock.t += 10.0
    api.create_pod(make_pod("late-1"))
    m = s2.run_cycle()
    assert not s2.is_leader and m.bound == 0
    # Past the TTL the standby wins the CAS and schedules the backlog.
    clock.t += 6.0
    m = s2.run_cycle()
    assert s2.is_leader and m.bound == 1


def test_clean_handoff_on_close():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock)
    s1.run_cycle()
    s1.close()  # releases the lease — no TTL wait
    api.create_pod(make_pod("late-1"))
    m = s2.run_cycle()
    assert s2.is_leader and m.bound == 1


def test_leader_renews_by_scheduling():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock, lease_duration=15.0)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock, lease_duration=15.0)
    for _ in range(4):  # each cycle renews; 4 x 10s > TTL but never lapses
        s1.run_cycle()
        clock.t += 10.0
        s2.run_cycle()
    assert s1.is_leader and not s2.is_leader


def test_lease_failure_fails_safe():
    """If the lease endpoint is unreachable, the scheduler must STAND BY —
    an ex-leader that cannot prove leadership never schedules."""
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)

    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s1.run_cycle()
    assert s1.is_leader

    from tpu_scheduler.runtime.fake_api import ApiError

    orig = api.acquire_lease
    api.acquire_lease = lambda *a, **k: (_ for _ in ()).throw(ApiError(503, "lease backend down"))
    try:
        api.create_pod(make_pod("late-1"))
        m = s1.run_cycle()
    finally:
        api.acquire_lease = orig
    assert not s1.is_leader and m.bound == 0


def test_leader_election_over_http():
    from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient, RemoteApiAdapter

    api = FakeApiServer()
    server = HttpApiServer(api).start()
    try:
        _cluster(api, pods=4)
        a1 = RemoteApiAdapter(KubeApiClient(server.base_url))
        a2 = RemoteApiAdapter(KubeApiClient(server.base_url))
        s1 = Scheduler(a1, NativeBackend(), leader_elect=True, identity="s1")
        s2 = Scheduler(a2, NativeBackend(), leader_elect=True, identity="s2")
        m1 = s1.run_cycle()
        m2 = s2.run_cycle()
        assert s1.is_leader and not s2.is_leader
        assert m1.bound == 4 and m2.bound == 0
        s1.close()  # release over HTTP
        api.create_pod(make_pod("late-1"))
        m = s2.run_cycle()
        assert s2.is_leader and m.bound == 1
    finally:
        server.stop()
