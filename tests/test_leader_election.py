"""Lease-based leader election (SURVEY.md §5 — the reference has none):
only the lease holder schedules; standbys keep warm caches and take over
within the lease TTL of the leader vanishing, or immediately on clean
hand-off."""

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _cluster(api, pods=6):
    api.load(
        nodes=[make_node(f"n{i}", cpu="8", memory="32Gi") for i in range(2)],
        pods=[make_pod(f"p{i}") for i in range(pods)],
    )


def test_only_leader_schedules():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock)
    m1 = s1.run_cycle()  # acquires the lease, schedules
    m2 = s2.run_cycle()  # standby: lease held
    assert s1.is_leader and not s2.is_leader
    assert m1.bound == 6 and m2.bound == 0
    assert s1.metrics.snapshot()["scheduler_leadership_acquisitions_total"] == 1
    assert "scheduler_leadership_acquisitions_total" not in s2.metrics.snapshot()


def test_standby_takes_over_after_lease_expiry():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock, lease_duration=15.0)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock, lease_duration=15.0)
    s1.run_cycle()
    assert s1.is_leader
    # Leader dies silently (stops renewing); lease not yet expired.
    clock.t += 10.0
    api.create_pod(make_pod("late-1"))
    m = s2.run_cycle()
    assert not s2.is_leader and m.bound == 0
    # Past the TTL the standby wins the CAS and schedules the backlog.
    clock.t += 6.0
    m = s2.run_cycle()
    assert s2.is_leader and m.bound == 1


def test_clean_handoff_on_close():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock)
    s1.run_cycle()
    s1.close()  # releases the lease — no TTL wait
    api.create_pod(make_pod("late-1"))
    m = s2.run_cycle()
    assert s2.is_leader and m.bound == 1


def test_leader_renews_by_scheduling():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)
    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock, lease_duration=15.0)
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=clock, lease_duration=15.0)
    for _ in range(4):  # each cycle renews; 4 x 10s > TTL but never lapses
        s1.run_cycle()
        clock.t += 10.0
        s2.run_cycle()
    assert s1.is_leader and not s2.is_leader


def test_lease_failure_fails_safe():
    """If the lease endpoint is unreachable, the scheduler must STAND BY —
    an ex-leader that cannot prove leadership never schedules."""
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _cluster(api, pods=2)

    s1 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=clock)
    s1.run_cycle()
    assert s1.is_leader

    from tpu_scheduler.runtime.fake_api import ApiError

    orig = api.acquire_lease
    api.acquire_lease = lambda *a, **k: (_ for _ in ()).throw(ApiError(503, "lease backend down"))
    try:
        api.create_pod(make_pod("late-1"))
        m = s1.run_cycle()
    finally:
        api.acquire_lease = orig
    assert not s1.is_leader and m.bound == 0


def test_close_joins_renewal_thread_before_release():
    """Shutdown race regression: a background renewal already past its
    stop-check must never re-acquire the lease AFTER close() released it —
    the zombie holder would block every standby until the TTL lapsed.
    close() now joins the renewal thread before releasing; the
    FakeApiServer lease-write history proves the release is the final
    write.  The gate below holds an in-flight renewal open across the
    shutdown window, which the OLD close() (stop-without-join) lost to."""
    import threading
    import time

    api = FakeApiServer()
    _cluster(api, pods=2)
    # Real wall clock + a short TTL so the renewal thread fires quickly.
    sched = Scheduler(api, NativeBackend(), leader_elect=True, identity="s1", clock=time.monotonic, lease_duration=0.3)
    sched.run_cycle()
    assert sched.is_leader and sched._renew_thread is not None

    main_thread = threading.current_thread()
    in_renew = threading.Event()
    release_ran = threading.Event()
    orig_acquire = api.acquire_lease
    orig_release = api.release_lease

    def gated_acquire(name, holder, duration):
        if threading.current_thread() is not main_thread:
            in_renew.set()
            # Hold the renewal mid-flight: with the old stop-without-join
            # close(), the release overtakes this acquire and the renewal
            # lands AFTER it (the zombie-holder bug).  With the join fix,
            # close() waits here, the renewal completes FIRST, and the
            # release stays the final lease write.
            release_ran.wait(timeout=1.0)
        return orig_acquire(name, holder, duration)

    def tracked_release(name, holder):
        release_ran.set()
        return orig_release(name, holder)

    api.acquire_lease = gated_acquire
    api.release_lease = tracked_release
    try:
        assert in_renew.wait(timeout=5.0), "renewal thread never fired"
        sched.close()
    finally:
        api.acquire_lease = orig_acquire
        api.release_lease = orig_release
    assert sched._renew_thread is None
    history = [holder for name, holder in api.lease_history if name == sched.lease_name]
    assert "" in history, "close() must have released the lease"
    assert history[-1] == "", f"a renewal landed after the release: {history}"
    # And the lease is immediately takeable — no TTL wait for a standby.
    s2 = Scheduler(api, NativeBackend(), leader_elect=True, identity="s2", clock=time.monotonic)
    api.create_pod(make_pod("late-1"))
    m = s2.run_cycle()
    assert s2.is_leader and m.bound == 1
    s2.close()


def test_leader_election_over_http():
    from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient, RemoteApiAdapter

    api = FakeApiServer()
    server = HttpApiServer(api).start()
    try:
        _cluster(api, pods=4)
        a1 = RemoteApiAdapter(KubeApiClient(server.base_url))
        a2 = RemoteApiAdapter(KubeApiClient(server.base_url))
        s1 = Scheduler(a1, NativeBackend(), leader_elect=True, identity="s1")
        s2 = Scheduler(a2, NativeBackend(), leader_elect=True, identity="s2")
        m1 = s1.run_cycle()
        m2 = s2.run_cycle()
        assert s1.is_leader and not s2.is_leader
        assert m1.bound == 4 and m2.bound == 0
        s1.close()  # release over HTTP
        api.create_pod(make_pod("late-1"))
        m = s2.run_cycle()
        assert s2.is_leader and m.bound == 1
    finally:
        server.stop()


def test_lease_conformance_spec_shaped_http():
    """VERDICT r3 #6: the election rides ONLY the real coordination.k8s.io
    surface — GET/POST/PUT Lease objects with resourceVersion CAS; no
    invented verbs.  This drives the HTTP routes with raw spec-shaped
    requests, the way any kube client would."""
    import json

    from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient
    from tpu_scheduler.runtime.lease import LEASE_NAMESPACE, make_lease

    api = FakeApiServer()
    server = HttpApiServer(api).start()
    try:
        client = KubeApiClient(server.base_url)
        path = f"/apis/coordination.k8s.io/v1/namespaces/{LEASE_NAMESPACE}/leases"

        # GET before create -> 404 (a real apiserver's answer, not a verb error)
        code, _ = client._request_json("GET", f"{path}/sched")
        assert code == 404

        # CREATE via POST -> 201 with a server-assigned resourceVersion
        lease = make_lease(LEASE_NAMESPACE, "sched", "s1", 15, now=1000.0)
        code, created = client._request_json("POST", path, lease)
        assert code == 201
        rv1 = created["metadata"]["resourceVersion"]
        assert created["spec"]["holderIdentity"] == "s1"
        assert created["spec"]["renewTime"]  # MicroTime string

        # duplicate CREATE -> 409
        code, _ = client._request_json("POST", path, lease)
        assert code == 409

        # UPDATE with the current rv -> 200, rv advances
        created["spec"]["renewTime"] = make_lease(LEASE_NAMESPACE, "sched", "s1", 15, 1010.0)["spec"]["renewTime"]
        code, updated = client._request_json("PUT", f"{path}/sched", created)
        assert code == 200 and updated["metadata"]["resourceVersion"] != rv1

        # UPDATE with the STALE rv -> 409 Conflict (the CAS races resolve by)
        stale = json.loads(json.dumps(created))
        stale["metadata"]["resourceVersion"] = rv1
        stale["spec"]["holderIdentity"] = "s2"
        code, _ = client._request_json("PUT", f"{path}/sched", stale)
        assert code == 409

        # the takeover CAS with the fresh rv succeeds
        fresh = json.loads(json.dumps(updated))
        fresh["spec"]["holderIdentity"] = "s2"
        code, final = client._request_json("PUT", f"{path}/sched", fresh)
        assert code == 200 and final["spec"]["holderIdentity"] == "s2"
    finally:
        server.stop()


def test_election_algorithm_unit():
    """runtime/lease.py try_acquire_or_renew against an in-memory CAS store:
    create, renew, fresh-lease denial, expiry takeover, lost-race conflict,
    release -> immediate takeover."""
    from tpu_scheduler.runtime import lease as lm

    store = {}

    def get():
        return json_copy(store.get("l"))

    def json_copy(x):
        import json

        return json.loads(json.dumps(x)) if x is not None else None

    def make_cas():
        def create(obj):
            if "l" in store:
                return False
            store["l"] = {**obj, "metadata": {**obj["metadata"], "resourceVersion": "1"}}
            return True

        def update(obj):
            cur = store.get("l")
            if cur is None or obj["metadata"]["resourceVersion"] != cur["metadata"]["resourceVersion"]:
                return False
            store["l"] = {**obj, "metadata": {**obj["metadata"], "resourceVersion": str(int(cur["metadata"]["resourceVersion"]) + 1)}}
            return True

        return create, update

    create, update = make_cas()
    kw = dict(namespace="ns", name="l", duration_seconds=15)
    assert lm.try_acquire_or_renew(get, create, update, holder="a", now=100.0, **kw)  # create
    assert lm.try_acquire_or_renew(get, create, update, holder="a", now=110.0, **kw)  # renew
    assert store["l"]["spec"]["leaseTransitions"] == 0
    assert not lm.try_acquire_or_renew(get, create, update, holder="b", now=110.0, **kw)  # held, fresh
    assert lm.try_acquire_or_renew(get, create, update, holder="b", now=126.0, **kw)  # expired takeover
    assert store["l"]["spec"]["leaseTransitions"] == 1
    # lost race: another writer bumps rv between GET and PUT

    def racing_update(obj):
        store["l"]["metadata"]["resourceVersion"] = "99"  # concurrent writer
        return update(obj)

    assert not lm.try_acquire_or_renew(get, create, racing_update, holder="a", now=200.0, **kw)
    # release -> empty holder -> immediate takeover regardless of TTL
    store["l"]["metadata"]["resourceVersion"] = "5"
    lm.release(get, update, holder=store["l"]["spec"]["holderIdentity"], now=210.0)
    assert store["l"]["spec"]["holderIdentity"] == ""
    assert lm.try_acquire_or_renew(get, create, update, holder="c", now=210.5, **kw)


def test_concurrent_acquire_race_single_winner():
    """Two clients race acquire over real HTTP sockets: the rv CAS must
    yield EXACTLY one holder per round, every round, with the loser reading
    a clean False (no 5xx, no double leadership)."""
    import threading

    from tpu_scheduler.runtime.http_api import HttpApiServer, KubeApiClient

    api = FakeApiServer()
    server = HttpApiServer(api).start()
    try:
        c1, c2 = KubeApiClient(server.base_url), KubeApiClient(server.base_url)
        for round_no in range(12):
            results = {}
            barrier = threading.Barrier(2)

            def race(name, client):
                barrier.wait()
                results[name] = client.acquire_lease("race-lease", name, duration_seconds=15)

            t1 = threading.Thread(target=race, args=("a", c1))
            t2 = threading.Thread(target=race, args=("b", c2))
            t1.start(); t2.start(); t1.join(); t2.join()
            winners = [k for k, v in results.items() if v]
            # Round 0: both race the create, the CAS admits exactly one.
            # Later rounds: the incumbent renews (holder==self), the
            # challenger sees a fresh lease (or loses the CAS) — still
            # exactly one winner, and it is the recorded holder.
            assert len(winners) == 1, (round_no, results)
            holder = (api.get_lease_object("kube-system", "race-lease") or {}).get("spec", {}).get("holderIdentity")
            assert holder == winners[0], (round_no, holder, results)
    finally:
        server.stop()
