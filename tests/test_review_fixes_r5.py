"""Round-3 advisor fixes: upload-cache finalizers keyed per-weakref (id
reuse safe), the repack alloc-side scale guard, and NoExecute grace clocks
surviving checkpoint/restore."""

import weakref

import numpy as np
import pytest

from tpu_scheduler.api.objects import Taint, Toleration
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.backends.tpu import TpuBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod


# --- upload-cache finalizer per weakref --------------------------------------


class _Arr:  # weakref-able stand-in for a host array
    pass


def test_evict_ignores_id_reused_entry():
    """A finalizer firing AFTER its id was recycled to a new cached array
    must not evict the new owner's entry (the stored weakref's identity is
    the discriminator, not the id)."""
    b = TpuBackend(use_pallas=False)
    a1, a2 = _Arr(), _Arr()
    # Simulate: id K was cached for a1 (now dead in the story), then reused
    # for a2's entry.  a1's late finalizer carries a1's weakref.
    key = 12345
    wr1, wr2 = weakref.ref(a1), weakref.ref(a2)
    fin2 = weakref.finalize(a2, lambda: None)
    b._dev_cache[key] = (wr2, "buf2", fin2)
    b._evict(key, wr1)  # stale finalizer: wrong weakref -> no-op
    assert key in b._dev_cache
    b._evict(key, wr2)  # the entry's own finalizer evicts
    assert key not in b._dev_cache


def test_put_detaches_stale_finalizer_on_id_reuse():
    """Overwriting an id-reused entry detaches the old entry's finalizer so
    a late fire cannot pin or evict the new owner's buffer."""
    b = TpuBackend(use_pallas=False)
    old_owner = _Arr()
    old_fin = weakref.finalize(old_owner, lambda: None)
    arr = np.arange(8)
    b._dev_cache[id(arr)] = (weakref.ref(old_owner), "stale-buf", old_fin)
    buf = b._put(arr)
    assert not old_fin.alive, "stale finalizer must be detached on overwrite"
    ent = b._dev_cache[id(arr)]
    assert ent[1] is buf and ent[0]() is arr and ent[2].alive


def test_dead_array_evicts_its_entry():
    b = TpuBackend(use_pallas=False)
    arr = np.arange(16)
    b._put(arr)
    key = id(arr)
    assert key in b._dev_cache
    del arr
    import gc

    gc.collect()
    assert key not in b._dev_cache, "finalizer must evict the dead array's buffer"


def test_drop_dev_cache_detaches_finalizers():
    b = TpuBackend(use_pallas=False)
    arr = np.arange(16)
    b._put(arr)
    fin = b._dev_cache[id(arr)][2]
    b._drop_dev_cache()
    assert not fin.alive and not b._dev_cache
    # Re-upload of the still-alive array registers a fresh finalizer.
    b._put(arr)
    assert b._dev_cache[id(arr)][2].alive


# --- repack alloc-side scale guard -------------------------------------------


def test_repack_raises_when_extended_alloc_outgrows_scale():
    """round-3 advisor: a node update pushing an EXTENDED allocatable past
    INT32_MAX at the frozen divisor must force a full pack (which re-derives
    the divisor), not silently saturate capacity."""
    from dataclasses import replace as dc_replace

    from tpu_scheduler.api.objects import NodeStatus
    from tpu_scheduler.core.snapshot import ClusterSnapshot
    from tpu_scheduler.ops.pack import INT32_MAX, pack_snapshot, repack_avail, repack_incremental

    nodes = [make_node("n0", cpu="8", memory="32Gi", extended={"example.com/chips": 4})]
    pods = [make_pod("p0", extended={"example.com/chips": 1})]
    snap = ClusterSnapshot.build(nodes, pods)
    packed = pack_snapshot(snap)
    assert packed.res_scales[2] == 1  # small values: divisor 1

    grown = dc_replace(
        nodes[0],
        status=NodeStatus(allocatable={"cpu": "8", "memory": "32Gi", "example.com/chips": int(INT32_MAX) + 10}),
    )
    snap2 = ClusterSnapshot.build([grown], pods)
    with pytest.raises(ValueError, match="outgrown by node allocatable"):
        repack_avail(packed, snap2)
    with pytest.raises(ValueError, match="outgrown by node allocatable"):
        repack_incremental(packed, snap2)
    # The full pack cures it by re-deriving the divisor.
    repacked = pack_snapshot(snap2)
    assert repacked.res_scales[2] > 1


# --- NoExecute clocks survive checkpoint/restore -----------------------------


def test_noexecute_clock_survives_restart(tmp_path):
    """round-3 advisor: a scheduler restart must NOT grant tolerating pods a
    fresh tolerationSeconds window — the first-seen timestamps persist in
    the checkpoint, so the eviction deadline holds across hand-offs."""
    from tpu_scheduler.runtime.checkpoint import restore_scheduler, save_scheduler

    taint = Taint(key="maint", value="drain", effect="NoExecute")
    tol = Toleration(key="maint", operator="Equal", value="drain", effect="NoExecute", toleration_seconds=60)
    now = [1000.0]

    def build_api():
        api = FakeApiServer()
        api.load(
            nodes=[make_node("n1", cpu="8", memory="32Gi", taints=[taint])],
            pods=[make_pod("victim", cpu="1", node_name="n1", phase="Running", tolerations=[tol])],
        )
        return api

    api = build_api()
    s1 = Scheduler(api, NativeBackend(), requeue_seconds=0.0, clock=lambda: now[0])
    s1.run_cycle()  # grace clock starts at t=1000
    now[0] = 1040.0
    s1.run_cycle()  # 40s elapsed, still inside the 60s window
    assert "victim" in {p.metadata.name for p in api.list_pods()}
    save_scheduler(s1, str(tmp_path))  # checkpoints 40s ELAPSED

    # Restart (clocks are process-local/monotonic, so the checkpoint stores
    # elapsed time, like the requeue ledger): the successor inherits the 40s
    # of progress instead of granting a fresh 60s window.
    api2 = build_api()
    s2 = Scheduler(api2, NativeBackend(), requeue_seconds=0.0, clock=lambda: now[0])
    assert restore_scheduler(s2, str(tmp_path))
    s2.run_cycle()
    assert "victim" in {p.metadata.name for p in api2.list_pods()}  # 40s < 60
    now[0] = 1065.0  # 65s total since the ORIGINAL first sighting
    s2.run_cycle()
    assert "victim" not in {p.metadata.name for p in api2.list_pods()}, (
        "the restored clock must carry the pre-restart elapsed time"
    )

    # Control: without the restore, a fresh scheduler resets the window and
    # keeps the pod at the same instant.
    api3 = build_api()
    s3 = Scheduler(api3, NativeBackend(), requeue_seconds=0.0, clock=lambda: now[0])
    s3.run_cycle()
    assert "victim" in {p.metadata.name for p in api3.list_pods()}


def test_dev_cache_capped_under_churn():
    """On zero-copy platforms (CPU device_put aliases the host buffer) the
    cached device array keeps its host array alive, so weakref eviction
    never fires — the LRU cap must bound the cache in a long daemon
    (found by a churn soak), with hot entries surviving over churned ones."""
    b = TpuBackend(use_pallas=False)
    b._dev_cache_cap = 8
    hot = np.arange(4)
    keep = []  # keep churn arrays alive so weakref eviction can't help
    for i in range(50):
        b._put(hot)  # hot entry re-touched every iteration
        a = np.full(4, i)
        keep.append(a)
        b._put(a)
    assert len(b._dev_cache) <= 8
    assert id(hot) in b._dev_cache, "recently-touched entry must survive the cap"
    # every evicted entry's finalizer was detached; survivors' are alive
    assert all(ent[2].alive for ent in b._dev_cache.values())


def test_incremental_snapshot_equivalence():
    """ClusterReflector.snapshot() (incremental by-node index, round 5) must
    equal ClusterSnapshot.build over the reflector stores — placements,
    by-node lists, pending sets — through create/bind/delete churn, and
    return the SAME object when nothing changed."""
    from tpu_scheduler.api.objects import ObjectReference, PodAntiAffinityTerm
    from tpu_scheduler.core.snapshot import ClusterSnapshot
    from tpu_scheduler.runtime.reflector import ClusterReflector

    api = FakeApiServer()
    for i in range(6):
        api.create_node(make_node(f"n{i}", cpu="8", memory="32Gi", labels={"zone": f"z{i%2}"}))
    term = [PodAntiAffinityTerm(match_labels={"app": "a"}, topology_key="zone")]
    for i in range(10):
        api.create_pod(make_pod(f"b{i}", cpu="1", memory="1Gi", node_name=f"n{i % 6}",
                                labels={"app": "a"} if i % 3 == 0 else None,
                                anti_affinity=term if i % 3 == 0 else None, phase="Running"))
    for i in range(8):
        api.create_pod(make_pod(f"p{i}", cpu="1", memory="1Gi"))
    refl = ClusterReflector(api)
    refl.sync()

    def check():
        inc = refl.snapshot()
        ref = ClusterSnapshot.build(refl.nodes.state(), refl.pods.state())
        assert {p.metadata.name for p in inc.pods} == {p.metadata.name for p in ref.pods}
        for n in ref.nodes:
            assert [id(p) for p in inc.pods_on_node(n.name)] == [id(p) for p in ref.pods_on_node(n.name)]
        assert {(id(p), n.name) for p, n in inc.placed_pods()} == {(id(p), n.name) for p, n in ref.placed_pods()}
        assert {(id(p), n.name) for p, n in inc.placed_pods_with_terms()} == {
            (id(p), n.name) for p, n in ref.placed_pods_with_terms()
        }
        assert [p.metadata.name for p in inc.pending_pods()] == [p.metadata.name for p in ref.pending_pods()]
        return inc

    s1 = check()
    assert refl.snapshot() is s1  # no events -> same (cached) snapshot
    # churn: bind two, delete one bound + one pending, add one
    api.create_binding("default", "p0", ObjectReference(name="n3"))
    api.create_binding("default", "p1", ObjectReference(name="n3"))
    api.delete_pod("default", "b0")
    api.delete_pod("default", "p2")
    api.create_pod(make_pod("fresh", cpu="1", memory="1Gi"))
    refl.sync()
    s2 = check()
    assert s2 is not s1
    # the OLD snapshot must be untouched by later churn (copy-on-write)
    assert any(p.metadata.name == "b0" for p in s1.pods_on_node("n0"))
    assert all(p.metadata.name != "p0" for p in s1.pods_on_node("n3"))
