"""Gang (coscheduling) admission: pods sharing spec.gang bind all-or-nothing
within a cycle — the TPU training-job shape (runtime/controller.py
_solve_gang_aware)."""

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod, synth_cluster


def test_complete_gang_binds():
    api = FakeApiServer()
    api.load(
        nodes=[make_node(f"n{i}", cpu="8", memory="32Gi") for i in range(2)],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 4 and m.unschedulable == 0
    assert sched.metrics.snapshot()["scheduler_gangs_admitted_total"] == 1


def test_partial_gang_binds_nothing():
    """Capacity for 3 of 4 members: the whole gang must stay pending."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="3", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 4
    assert all(p.spec.node_name is None for p in api.list_pods())
    assert sched.metrics.snapshot()["scheduler_gang_rejections_total"] == 1


def test_gang_admits_when_capacity_arrives():
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="3", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.run_cycle()
    api.create_node(make_node("n2", cpu="3", memory="32Gi"))
    m = sched.run_cycle()
    assert m.bound == 4
    assert all(p.spec.node_name is not None for p in api.list_pods())


def test_gang_does_not_block_independent_pods():
    """An incomplete gang requeues whole; unrelated pods in the same cycle
    still bind (and the capacity the gang momentarily held is reclaimed by
    the next cycle)."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="4", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="2", memory="1Gi", gang="job-1", priority=5) for i in range(3)]
        + [make_pod("solo", cpu="1", memory="1Gi")],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    # gang needs 6 cores, node has 4 -> gang requeues whole; solo binds
    # (this cycle or next — the auction may have ceded its capacity view).
    sched.run(until_settled=True, max_cycles=4)
    placed = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
    assert placed == {"solo"}
    assert m.unschedulable >= 1


def test_pipelined_gang_filtering():
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="3", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, pipeline=True)
    sched.run(until_settled=True, max_cycles=4)
    assert all(p.spec.node_name is None for p in api.list_pods())
    assert sched._assumed == {}  # nothing dispatched for the rejected gang


def test_synth_gangs_schedule_atomically():
    snap = synth_cluster(n_nodes=16, n_pending=80, n_bound=16, seed=4, gang_fraction=0.3)
    gangs: dict[str, int] = {}
    for p in snap.pending_pods():
        if p.spec.gang:
            gangs[p.spec.gang] = gangs.get(p.spec.gang, 0) + 1
    assert gangs and max(gangs.values()) >= 2
    api = FakeApiServer()
    api.load(snap.nodes, snap.pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.run(until_settled=True, max_cycles=6)
    # Atomicity invariant: every gang is fully placed or fully pending.
    placed = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
    for g, size in gangs.items():
        members = [p.metadata.name for p in snap.pending_pods() if p.spec.gang == g]
        n_placed = sum(1 for m in members if m in placed)
        assert n_placed in (0, size), (g, n_placed, size)


def test_gang_split_across_pools_requeues_whole():
    """Cycle-wide membership: a gang whose members pin DIFFERENT pools can
    never look complete to any one pool shard — both halves requeue (no
    partial placement), exactly the atomicity contract."""
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE

    api = FakeApiServer()
    api.load(
        nodes=[
            make_node("a1", cpu="8", memory="32Gi", labels={"pool": "a"}),
            make_node("b1", cpu="8", memory="32Gi", labels={"pool": "b"}),
        ],
        pods=[
            make_pod("g-a", cpu="1", memory="1Gi", gang="split", node_selector={"pool": "a"}),
            make_pod("g-b", cpu="1", memory="1Gi", gang="split", node_selector={"pool": "b"}),
            make_pod("solo-a", cpu="1", memory="1Gi", node_selector={"pool": "a"}),
            make_pod("solo-b", cpu="1", memory="1Gi", node_selector={"pool": "b"}),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=DEFAULT_PROFILE.with_(pool_key="pool"), requeue_seconds=0.0)
    m = sched.run_cycle()
    placed = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
    assert placed == {"solo-a", "solo-b"}  # the split gang placed NOTHING
    assert m.unschedulable == 2


def test_gang_member_never_preempts_individually():
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE

    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="4", memory="16Gi")],
        pods=[
            make_pod("victim", cpu="4", memory="4Gi", node_name="n1", phase="Running", priority=0),
            make_pod("g-1", cpu="2", memory="1Gi", gang="j", priority=9),
            make_pod("g-2", cpu="64", memory="1Gi", gang="j", priority=9),  # can never fit
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=DEFAULT_PROFILE.with_(preemption=True), requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 0
    pods = {p.metadata.name for p in api.list_pods()}
    assert "victim" in pods  # nothing was evicted for half a gang
    assert sched.metrics.snapshot().get("scheduler_preemptions_total", 0) == 0


def test_sample_policy_refuses_gang_pods():
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="8", memory="32Gi")],
        pods=[make_pod("g-1", cpu="1", memory="1Gi", gang="j"), make_pod("solo", cpu="1", memory="1Gi")],
    )
    sched = Scheduler(api, NativeBackend(), policy="sample", requeue_seconds=0.0)
    m = sched.run_cycle()
    assert m.bound == 1 and m.unschedulable == 1
    placed = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
    assert placed == {"solo"}


def test_gang_member_in_backoff_blocks_the_rest():
    """A gang member still in requeue backoff makes the gang incomplete for
    everyone — the eligible members must NOT bind alone (review repro)."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="2", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="j") for i in range(3)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=60.0)
    sched.run_cycle()  # capacity for 2 of 3 -> whole gang rejected, 60s backoff
    assert all(p.spec.node_name is None for p in api.list_pods())
    api.create_pod(make_pod("w3", cpu="1", memory="1Gi", gang="j"))  # 4th member arrives
    m = sched.run_cycle()  # w3 eligible, w0-w2 in backoff: gang still incomplete
    assert m.bound == 0
    assert all(p.spec.node_name is None for p in api.list_pods())


def test_gang_refused_by_host_constrained_fallback():
    """UntensorizableConstraints -> host sequential phase: gang pods are
    refused there (atomicity cannot be expressed), the whole gang requeues."""
    from tpu_scheduler.api.objects import PodAntiAffinityTerm

    # Force the fallback via the budget knob (a cluster exceeding the
    # shipped defaults would need 256+ distinct terms — the knob states the
    # intent directly and keeps the test fast).
    nodes = [make_node(f"n{i}", cpu="64", memory="256Gi", labels={"name": f"n{i}"}) for i in range(4)]
    pods = []
    for i in range(8):
        term = [PodAntiAffinityTerm(match_labels={"app": f"a{i}"}, topology_key="name")]
        pods.append(make_pod(f"c{i}", cpu="100m", memory="64Mi", labels={"app": f"a{i}"}, anti_affinity=term))
    pods.append(make_pod("g-ok", cpu="100m", memory="64Mi", gang="j"))
    pods.append(make_pod("g-big", cpu="999", memory="64Mi", gang="j"))  # can never fit
    api = FakeApiServer()
    api.load(nodes, pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, constraint_budgets={"max_aa_terms": 4})
    sched.run(until_settled=True, max_cycles=4)
    counters = sched.metrics.snapshot()
    assert counters.get("scheduler_constraint_host_fallbacks_total", 0) >= 1
    placed = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
    assert "g-ok" not in placed and "g-big" not in placed  # atomicity held


def test_constrained_gang_binds_in_host_phase():
    """Round-5 (VERDICT r4 #4): a CONSTRAINED gang in an untensorizable
    cluster used to requeue forever (the host phase refused gangs); the
    host phase now trial-places the gang's members through the sequential
    chain and commits all-or-nothing."""
    from tpu_scheduler.api.objects import PodAntiAffinityTerm

    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi", labels={"name": f"n{i}"}) for i in range(4)]
    pods = []
    for i in range(8):  # untensorizable vocabulary (budget knob below)
        term = [PodAntiAffinityTerm(match_labels={"app": f"a{i}"}, topology_key="name")]
        pods.append(make_pod(f"c{i}", cpu="100m", memory="64Mi", labels={"app": f"a{i}"}, anti_affinity=term))
    # The gang itself is constrained: members repel each other, one per node.
    gterm = [PodAntiAffinityTerm(match_labels={"job": "g"}, topology_key="name")]
    for i in range(3):
        pods.append(make_pod(f"g{i}", cpu="1", memory="1Gi", labels={"job": "g"}, anti_affinity=gterm, gang="j"))
    api = FakeApiServer()
    api.load(nodes, pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, constraint_budgets={"max_aa_terms": 4})
    m = sched.run_cycle()
    counters = sched.metrics.snapshot()
    assert counters.get("scheduler_constraint_host_fallbacks_total", 0) >= 1  # really the host phase
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods() if p.spec.node_name}
    assert {"g0", "g1", "g2"} <= set(placed), placed
    assert len({placed[f"g{i}"] for i in range(3)}) == 3  # anti-affinity honored
    assert m.bound == 11  # everything placed, gang included
    assert counters.get("scheduler_gangs_admitted_total", 0) == 1


def test_constrained_gang_rejects_whole_in_host_phase():
    """Trial placement fails for one member -> the whole gang requeues, with
    the dedicated rejection metric (never a silent per-pod refusal)."""
    from tpu_scheduler.api.objects import PodAntiAffinityTerm

    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi", labels={"name": f"n{i}"}) for i in range(2)]
    pods = []
    for i in range(8):
        term = [PodAntiAffinityTerm(match_labels={"app": f"a{i}"}, topology_key="name")]
        pods.append(make_pod(f"c{i}", cpu="100m", memory="64Mi", labels={"app": f"a{i}"}, anti_affinity=term))
    gterm = [PodAntiAffinityTerm(match_labels={"job": "g"}, topology_key="name")]
    for i in range(3):  # 3 mutually-repelling members, 2 nodes -> impossible
        pods.append(make_pod(f"g{i}", cpu="1", memory="1Gi", labels={"job": "g"}, anti_affinity=gterm, gang="j"))
    api = FakeApiServer()
    api.load(nodes, pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, constraint_budgets={"max_aa_terms": 4})
    sched.run_cycle()
    counters = sched.metrics.snapshot()
    assert counters.get("scheduler_gang_host_rejections_total", 0) == 1
    assert all(p.spec.node_name is None for p in api.list_pods() if p.metadata.name.startswith("g"))


def test_split_constrained_gang_refused_with_metric():
    """A gang with members outside the host phase's view (one member in
    requeue backoff) cannot be admitted atomically by that scope: its local
    members refuse, counted in scheduler_gang_host_refusals_total."""
    from tpu_scheduler.api.objects import PodAntiAffinityTerm

    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi", labels={"name": f"n{i}"}) for i in range(4)]
    pods = []
    for i in range(8):
        term = [PodAntiAffinityTerm(match_labels={"app": f"a{i}"}, topology_key="name")]
        pods.append(make_pod(f"c{i}", cpu="100m", memory="64Mi", labels={"app": f"a{i}"}, anti_affinity=term))
    gterm = [PodAntiAffinityTerm(match_labels={"job": "g"}, topology_key="name")]
    for i in range(2):
        pods.append(make_pod(f"g{i}", cpu="1", memory="1Gi", labels={"job": "g"}, anti_affinity=gterm, gang="j"))
    api = FakeApiServer()
    api.load(nodes, pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=3600.0, constraint_budgets={"max_aa_terms": 4})
    # Put a third member into a long backoff before it ever becomes
    # schedulable: create it, fail it once via zero capacity… simpler: mark
    # the requeue ledger directly (the unit under test is the scope check).
    api.create_pod(make_pod("g-late", cpu="1", memory="1Gi", labels={"job": "g"}, anti_affinity=gterm, gang="j"))
    import time as _time

    sched.requeue_at["default/g-late"] = _time.monotonic() + 3600.0
    sched.run_cycle()
    counters = sched.metrics.snapshot()
    assert counters.get("scheduler_gang_host_refusals_total", 0) == 1
    assert all(p.spec.node_name is None for p in api.list_pods() if p.metadata.name.startswith("g"))


def test_gang_sample_policy_refusal_counted():
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="8", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="j") for i in range(3)],
    )
    sched = Scheduler(api, NativeBackend(), policy="sample", requeue_seconds=0.0)
    sched.run_cycle()
    assert sched.metrics.snapshot().get("scheduler_gang_sample_refusals_total", 0) == 1  # once per gang, not per pod


def test_split_gang_rejection_counted_once_per_cycle():
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE

    api = FakeApiServer()
    api.load(
        nodes=[
            make_node("a1", cpu="8", memory="32Gi", labels={"pool": "a"}),
            make_node("b1", cpu="8", memory="32Gi", labels={"pool": "b"}),
        ],
        pods=[
            make_pod("g-a", cpu="1", memory="1Gi", gang="split", node_selector={"pool": "a"}),
            make_pod("g-b", cpu="64", memory="1Gi", gang="split", node_selector={"pool": "b"}),  # never fits
            make_pod("x-a", cpu="1", memory="1Gi", node_selector={"pool": "a"}),
            make_pod("x-b", cpu="1", memory="1Gi", node_selector={"pool": "b"}),
        ],
    )
    sched = Scheduler(api, NativeBackend(), profile=DEFAULT_PROFILE.with_(pool_key="pool"), requeue_seconds=60.0)
    sched.run_cycle()
    assert sched.metrics.snapshot()["scheduler_gang_rejections_total"] == 1  # one gang, one count


def test_desynchronized_backoffs_do_not_livelock_the_gang():
    """Review repro: gang members whose requeue deadlines are desynchronized
    (a member arrived mid-backoff) must not ping-pong eligibility forever.
    On gang rejection the whole gang's deadlines are aligned, so the gang
    becomes eligible as a unit and binds once capacity allows."""
    now = [0.0]
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="8", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="j") for i in range(2)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=60.0, clock=lambda: now[0])
    # Make the 2-member gang unplaceable first: a blocker eats the node.
    api.create_pod(make_pod("blocker", cpu="8", memory="1Gi", priority=100))
    sched.run_cycle()  # blocker binds; gang rejected -> w0/w1 deadline 60
    assert {p.metadata.name for p in api.list_pods() if p.spec.node_name} == {"blocker"}
    api.delete_pod("default", "blocker")  # capacity frees up
    now[0] = 30.0
    api.create_pod(make_pod("w2", cpu="1", memory="1Gi", gang="j"))  # 3rd member, mid-backoff
    bound_names = set()
    for _ in range(40):  # cycle every 10s — shorter than the 60s backoff
        now[0] += 10.0
        sched.run_cycle()
        bound_names = {p.metadata.name for p in api.list_pods() if p.spec.node_name}
        if bound_names == {"w0", "w1", "w2"}:
            break
    assert bound_names == {"w0", "w1", "w2"}, f"gang livelocked; bound={bound_names}"


def test_placed_gang_members_are_not_preemption_victims():
    """Evicting one worker of a placed gang destroys the group's value for
    partial gain and would break all-or-nothing — members are victim-
    ineligible (found by the kitchen-sink preemption-wave invariant)."""
    from tpu_scheduler.models.profiles import DEFAULT_PROFILE

    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="4", memory="16Gi")],
        pods=[
            make_pod("g-0", cpu="2", gang="j", node_name="n1", phase="Running", priority=0),
            make_pod("g-1", cpu="2", gang="j", node_name="n1", phase="Running", priority=0),
            make_pod("vip", cpu="2", priority=100),
        ],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, profile=DEFAULT_PROFILE.with_(preemption=True))
    m = sched.run_cycle()
    assert m.bound == 0, "no victims available: the gang is whole or nothing"
    assert {p.metadata.name for p in api.list_pods()} >= {"g-0", "g-1"}


def test_gang_resolve_budget_exhaustion_is_counted():
    """VERDICT r3 weak #6: a cascade deeper than GANG_RESOLVE_BUDGET defers
    the remaining gangs' capacity to the next cycle — that event must be a
    metric, not a silent constant.  Budget 0 forces the exhaustion path for
    any incomplete gang; atomicity still holds (nothing partially binds)."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="3", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.GANG_RESOLVE_BUDGET = 0
    m = sched.run_cycle()
    counters = sched.metrics.snapshot()
    assert counters["scheduler_gang_resolve_budget_exhausted_total"] == 1
    assert m.bound == 0 and m.unschedulable == 4  # all-or-nothing held
    assert all(p.spec.node_name is None for p in api.list_pods())


def test_gang_resolve_budget_not_counted_on_normal_rejection():
    """An ordinary in-budget rejection (re-solve reallocates the capacity)
    must NOT count as exhaustion."""
    api = FakeApiServer()
    api.load(
        nodes=[make_node("n1", cpu="3", memory="32Gi")],
        pods=[make_pod(f"w{i}", cpu="1", memory="1Gi", gang="job-1") for i in range(4)]
        + [make_pod("loner", cpu="1", memory="1Gi")],
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    m = sched.run_cycle()
    counters = sched.metrics.snapshot()
    assert "scheduler_gang_resolve_budget_exhausted_total" not in counters
    assert m.bound == 1  # the loner takes the reallocated capacity


def test_gang_with_pod_affinity_chain_binds():
    """Round-5 review repro: a gang whose members form a multi-hop HARD
    pod-affinity chain (A needs B's label placed, B needs C's) must still
    bind — the PA-hope rule has to keep A alive until B's placement
    activates its term (the gang mop-up exclusion means a dropped gang
    member would livelock the whole gang forever)."""
    from tpu_scheduler.api.objects import PodAntiAffinityTerm

    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi", labels={"zone": f"z{i % 2}"}) for i in range(4)]
    chain_a = [PodAntiAffinityTerm(match_labels={"role": "b"}, topology_key="zone")]
    chain_b = [PodAntiAffinityTerm(match_labels={"role": "c"}, topology_key="zone")]
    pods = [
        make_pod("a", cpu="1", memory="1Gi", labels={"role": "a"}, pod_affinity=chain_a, gang="j"),
        make_pod("b", cpu="1", memory="1Gi", labels={"role": "b"}, pod_affinity=chain_b, gang="j"),
        make_pod("c", cpu="1", memory="1Gi", labels={"role": "c"}, gang="j"),
    ]
    api = FakeApiServer()
    api.load(nodes, pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.run(until_settled=True, max_cycles=4)
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods() if p.spec.node_name}
    assert {"a", "b", "c"} <= set(placed), placed
    assert sched.metrics.snapshot().get("scheduler_gangs_admitted_total", 0) == 1
