"""Multi-mesh fleet layer (tpu_scheduler/fleet): topology-keyed shard
assignment (DomainShardMap/ShardKeyer, hash-mode bit-parity with the flat
crc32), two-phase cross-replica gang reservations (all-or-nothing, TTL
reclaim, zero-orphan accounting), live shard resizing (published shard map,
disjoint-ownership invariant across split/merge without restart), checkpoint
v5 shard-map persistence with v4 migration, and the vectorized reflector
event fold (bit-parity with the scalar loop + microbench)."""

import json
import time

import numpy as np

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.delta.index import DeltaIndex
from tpu_scheduler.delta.state import SolveState, req64_of
from tpu_scheduler.fleet.keyer import KEYER_MODES, DomainShardMap, ShardKeyer
from tpu_scheduler.fleet.reservation import (
    GANG_RESERVATION_PREFIX,
    RESERVATION_STATES,
    GangReservationLedger,
    count_orphaned_reservations,
    reservation_lease_name,
)
from tpu_scheduler.fleet.resize import (
    SHARD_MAP_LEASE,
    decode_shard_map,
    encode_shard_map,
    publish_shard_map,
    read_shard_map,
)
from tpu_scheduler.runtime.checkpoint import restore_scheduler, save_scheduler
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.runtime.shards import (
    ShardSet,
    shard_for_name,
    shard_lease_name,
    shard_of_pod,
)
from tpu_scheduler.testing import make_node, make_pod
from tpu_scheduler.topology.model import TopologyModel

RACK_KEY = "topology.tpu-scheduler/rack"


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _racked_nodes(n, rack_size):
    return [
        make_node(f"n{i:03d}", cpu="64", memory="256Gi", labels={RACK_KEY: f"rack-{i // rack_size}"})
        for i in range(n)
    ]


def _topo(nodes):
    return TopologyModel.detect(nodes).compile(nodes)


# -- topology-keyed sharding (fleet/keyer.py) --------------------------------


def test_domain_map_partitions_contiguous_and_balanced():
    nodes = _racked_nodes(8, 2)  # 4 racks of 2, snapshot order n000..n007
    dm = DomainShardMap.compile(_topo(nodes), 2)
    assert dm.num_shards == 2
    assert dm.domains == ("rack-0", "rack-1", "rack-2", "rack-3")
    assert dm.domain_shard == (0, 0, 1, 1)
    # Each shard's node columns are a contiguous snapshot-order slice.
    assert dm.shard_nodes[0] == tuple(f"n{i:03d}" for i in range(4))
    assert dm.shard_nodes[1] == tuple(f"n{i:03d}" for i in range(4, 8))
    assert dm.domains_of_shard(0) == ("rack-0", "rack-1")
    assert dm.domains_of_shard(1) == ("rack-2", "rack-3")
    assert all(dm.node_shard[f"n{i:03d}"] == (0 if i < 4 else 1) for i in range(8))


def test_domain_map_never_splits_a_rack_and_stays_contiguous_when_uneven():
    # 10 nodes, rack size 3 -> racks of 3/3/3/1: boundaries land between
    # racks, never inside one, and concatenating the slices recovers the
    # exact snapshot order (contiguity).
    nodes = _racked_nodes(10, 3)
    dm = DomainShardMap.compile(_topo(nodes), 3)
    for dom, shard in zip(dm.domains, dm.domain_shard):
        owners = {dm.node_shard[n.metadata.name] for n in nodes if n.metadata.labels[RACK_KEY] == dom}
        assert owners == {shard}, (dom, owners)
    flat = tuple(name for slice_ in dm.shard_nodes for name in slice_)
    assert flat == tuple(n.metadata.name for n in nodes)
    assert sum(len(s) for s in dm.shard_nodes) == 10


def test_domain_map_is_deterministic_across_compiles():
    nodes = _racked_nodes(12, 4)
    a = DomainShardMap.compile(_topo(nodes), 4)
    b = DomainShardMap.compile(_topo(nodes), 4)
    assert a == b  # every replica derives the identical map


def test_domain_map_degenerate_inputs_return_none():
    nodes = _racked_nodes(4, 2)
    topo = _topo(nodes)
    assert DomainShardMap.compile(None, 4) is None  # topology-blind cluster
    assert DomainShardMap.compile(topo, 1) is None  # unsharded K
    assert DomainShardMap.compile(topo, 0) is None
    empty = TopologyModel.from_node_labels().compile([])
    assert DomainShardMap.compile(empty, 4) is None  # no nodes


def test_hash_mode_is_bit_identical_to_flat_crc32():
    k = ShardKeyer(4)
    assert k.mode == KEYER_MODES[1] == "hash"
    for i in range(200):
        key = f"default/p{i}"
        assert k.shard_for_key(key) == shard_for_name(key, 4)
    pods = [make_pod(f"p{i}") for i in range(50)]
    pods += [make_pod(f"g{i}", gang="train-job-7") for i in range(8)]
    pods += [make_pod("other-ns", namespace="team-a")]
    for p in pods:
        assert k.shard_of_pod(p) == shard_of_pod(p, 4)
    # No node columns in hash mode: the flat hash spans no topology slice.
    assert k.node_set([0, 1, 2, 3]) == set()


def test_topology_keyer_gang_atomicity_and_locality():
    nodes = _racked_nodes(16, 4)
    dm = DomainShardMap.compile(_topo(nodes), 4)
    k = ShardKeyer(4, dm)
    assert k.mode == KEYER_MODES[0] == "topology"
    # Every gang member keys by the GANG name: one owner, atomic admission.
    members = [make_pod(f"m{i}", gang="train-7") for i in range(12)]
    assert {k.shard_of_pod(p) for p in members} == {k.shard_for_key("train-7")}
    solo = make_pod("solo")
    assert k.shard_of_pod(solo) == k.shard_for_key("default/solo")
    # Keys spread over every shard and stay in range.
    seen = {k.shard_for_key(f"default/p{i}") for i in range(400)}
    assert seen == set(range(4))
    # node_set unions the slices; out-of-range shard ids are ignored.
    assert k.node_set([0]) == set(dm.shard_nodes[0])
    assert k.node_set([0, 3]) == set(dm.shard_nodes[0]) | set(dm.shard_nodes[3])
    assert k.node_set([99, -1]) == set()


def test_keyer_single_shard_degenerates_to_zero():
    nodes = _racked_nodes(4, 2)
    dm = DomainShardMap.compile(_topo(nodes), 2)
    k = ShardKeyer(1, dm)
    assert k.shard_for_key("anything") == 0


# -- cross-replica gang reservations (fleet/reservation.py) ------------------


def test_reserve_is_all_or_nothing_with_rollback():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    led = GangReservationLedger(api, "r1", 6.0, clock)
    assert reservation_lease_name("g1", 2).startswith(GANG_RESERVATION_PREFIX)
    assert led.reserve("g1", [1, 2]) is True
    assert api.get_lease(reservation_lease_name("g1", 1))["holder"] == "r1"
    assert led.active() == {"g1": [1, 2]}
    assert led.active_shards() == {1, 2}
    # Re-reserving an active gang renews, never double-counts.
    assert led.reserve("g1", [1, 2]) is True
    assert led.counts["reserved"] == 1
    # One refused peer CAS aborts the whole reservation and rolls back the
    # rows already taken.
    api.acquire_lease(reservation_lease_name("g2", 3), "r2", 60.0)
    assert led.reserve("g2", [1, 3]) is False
    assert api.get_lease(reservation_lease_name("g2", 1)) is None  # rolled back
    assert "g2" not in led.active()
    assert led.counts["aborted"] == 1
    # Commit releases the rows immediately (no TTL wait for the peers).
    assert led.commit("g1") is True
    assert api.get_lease(reservation_lease_name("g1", 1)) is None
    assert api.get_lease(reservation_lease_name("g1", 2)) is None
    assert led.counts["committed"] == 1
    assert led.commit("g1") is False  # already gone
    assert set(led.counts) == set(RESERVATION_STATES)


def test_crashed_owner_reservations_expire_within_one_ttl():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    led1 = GangReservationLedger(api, "r1", 6.0, clock)
    assert led1.reserve("wide", [0, 1]) is True
    # r1 crashes (stops renewing).  Before expiry the rows are orphaned and
    # refuse a peer's reservation.
    clock.t += 3.0
    assert count_orphaned_reservations(api, clock.t, {"r2"}) == 2
    led2 = GangReservationLedger(api, "r2", 6.0, clock)
    assert led2.reserve("wide", [0]) is False
    # Past the TTL the rows free with no survivor action: zero orphans, the
    # peer's reservation lands.
    clock.t += 4.0
    assert count_orphaned_reservations(api, clock.t, {"r2"}) == 0
    assert led2.reserve("wide", [0, 1]) is True
    # The crashed owner's next renew discovers the loss and reports EXPIRED.
    assert led1.renew() == 1
    assert led1.active() == {} and led1.counts["expired"] == 1
    # The live holder's rows are not orphans.
    assert count_orphaned_reservations(api, clock.t, {"r2"}) == 0


def test_partial_lease_loss_expires_once_and_releases_survivors():
    """The `expired` ledger-count regression: losing ANY peer lease expires
    the whole reservation exactly once (per gang, not per lost lease), the
    surviving rows are handed back in the same round, and the counts dict
    keeps the full RESERVATION_STATES key set — one source of truth."""
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    led = GangReservationLedger(api, "r1", 6.0, clock)
    assert led.reserve("wide", [0, 1, 2]) is True
    # A rival steals exactly one row (its TTL lapsed under brownout while
    # the others were renewed out-of-band) — the reservation is no longer
    # all-or-nothing and must expire as a unit.
    api.release_lease(reservation_lease_name("wide", 1), "r1")
    api.acquire_lease(reservation_lease_name("wide", 1), "r2", 60.0)
    assert led.renew() == 1
    assert led.counts["expired"] == 1  # once per gang, not per lost lease
    assert led.active() == {}
    # The survivors (shards 0 and 2) were released, not left to the TTL.
    assert api.get_lease(reservation_lease_name("wide", 0)) is None
    assert api.get_lease(reservation_lease_name("wide", 2)) is None
    # A second renew finds nothing active and counts nothing new.
    assert led.renew() == 0
    assert led.counts["expired"] == 1
    assert set(led.counts) == set(RESERVATION_STATES)


def test_abort_and_release_all_hand_rows_back_immediately():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    led = GangReservationLedger(api, "r1", 60.0, clock)  # long TTL: only release explains freeing
    assert led.reserve("a", [1]) and led.reserve("b", [2, 3])
    d = led.debug()
    assert d["active"] == {"a": [1], "b": [2, 3]}
    assert set(d["counts"]) == set(RESERVATION_STATES)
    assert led.abort("a") is True
    assert api.get_lease(reservation_lease_name("a", 1)) is None
    led.release_all()
    assert led.active() == {}
    assert api.get_lease(reservation_lease_name("b", 2)) is None
    assert led.counts["aborted"] == 2  # the explicit abort + release_all's
    assert count_orphaned_reservations(api, clock.t, set()) == 0


def test_orphan_count_is_vacuous_without_a_lease_collection_route():
    class NoListApi:
        pass

    assert count_orphaned_reservations(NoListApi(), 0.0, set()) == 0


# -- live shard resizing (fleet/resize.py + ShardSet) ------------------------


def test_shard_map_holder_string_encoding():
    assert encode_shard_map(3, 8) == "3:8"
    assert decode_shard_map("3:8") == (3, 8)
    for bad in (None, "", "x", "3", "a:b", "-1:4", "2:0", "1:2:3x", 7):
        assert decode_shard_map(bad) is None, bad


def test_publish_is_monotonic_and_read_ignores_expiry():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    assert read_shard_map(api) is None  # never published
    assert publish_shard_map(api, 1, 8, 2.0) is True
    assert read_shard_map(api) == (1, 8)
    assert api.get_lease(SHARD_MAP_LEASE)["holder"] == "1:8"
    # A stale publisher (generation not above the published one) is refused.
    assert publish_shard_map(api, 1, 2, 2.0) is False
    assert publish_shard_map(api, 0, 16, 2.0) is False
    assert publish_shard_map(api, 2, 2, 2.0) is True
    # The map outlives its lease TTL: configuration, not liveness.
    clock.t += 100.0
    assert read_shard_map(api) == (2, 2)
    assert publish_shard_map(api, 3, 16, 2.0) is True
    assert read_shard_map(api) == (3, 16)


def test_live_split_and_merge_keep_ownership_disjoint_without_restart():
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    s1 = ShardSet(api, 4, "r1", 6.0, clock)
    s2 = ShardSet(api, 4, "r2", 6.0, clock)

    def settle(rounds=4):
        for _ in range(rounds):
            s1.refresh()
            s2.refresh()
            clock.t += 1.0
            # The invariant under test: at no refresh round do two live
            # replicas ever own the same shard.
            assert not (set(s1.owned) & set(s2.owned)), (s1.owned, s2.owned)

    settle()
    assert set(s1.owned) | set(s2.owned) == {0, 1, 2, 3}
    # Split 4 -> 8: published by the shard-0 coordinator, adopted
    # fleet-wide on the refresh cadence — no process restarted.
    coord, other = (s1, s2) if 0 in s1.owned else (s2, s1)
    assert other.publish_resize(8) is False  # only the shard-0 owner coordinates
    assert coord.publish_resize(8) is True
    settle()
    assert s1.num_shards == s2.num_shards == 8
    assert s1.map_generation == s2.map_generation >= 1
    assert set(s1.owned) | set(s2.owned) == set(range(8))
    assert len(s1.owned) == len(s2.owned) == 4  # proportional target holds
    # Merge 8 -> 2: leases beyond the new range release on adoption.
    coord = s1 if 0 in s1.owned else s2
    assert coord.publish_resize(2) is True
    settle()
    assert s1.num_shards == s2.num_shards == 2
    assert set(s1.owned) | set(s2.owned) == {0, 1}
    for s in range(2, 8):
        assert api.get_lease(shard_lease_name(s)) is None, s


# -- checkpoint v5 / v4 migration -------------------------------------------


def _sched(api, clock, identity="r1", shards=4):
    return Scheduler(api, NativeBackend(), shards=shards, identity=identity, clock=clock, lease_duration=6.0)


def _load(api, nodes=2):
    api.load(nodes=[make_node(f"n{i}", cpu="64", memory="256Gi") for i in range(nodes)], pods=[])


def test_checkpoint_v5_roundtrips_adopted_shard_map(tmp_path):
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _load(api)
    s = _sched(api, clock)
    s.run_cycle()
    assert publish_shard_map(api, 1, 8, 6.0) is True
    clock.t += 1.0
    s.run_cycle()  # the refresh round adopts the split
    assert s.shard_set.num_shards == 8 and s.shard_set.map_generation == 1
    save_scheduler(s, str(tmp_path))
    state = json.load(open(tmp_path / "state.json"))
    assert state["version"] == 5
    assert state["shard_map"] == {"generation": 1, "num_shards": 8, "keyer": "hash"}

    # Restore into a replica constructed on the deploy-time K=4: it resumes
    # on the adopted K=8 instead of racing the old count against peers.
    clock2 = FakeClock(5000.0)
    api2 = FakeApiServer(clock=clock2)
    _load(api2)
    s2 = _sched(api2, clock2)
    assert restore_scheduler(s2, str(tmp_path)) is True
    assert s2.shard_set.num_shards == 8 and s2.shard_set.map_generation == 1
    assert s2.num_shards == 8
    # A NEWER published map still wins on the first refresh round.
    assert publish_shard_map(api2, 2, 2, 6.0) is True
    s2.run_cycle()
    assert s2.shard_set.num_shards == 2 and s2.shard_set.map_generation == 2


def test_checkpoint_without_resize_omits_shard_map(tmp_path):
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _load(api)
    s = _sched(api, clock)
    s.run_cycle()
    save_scheduler(s, str(tmp_path))
    state = json.load(open(tmp_path / "state.json"))
    assert state["version"] == 5 and state["shard_map"] is None


def test_checkpoint_v4_migrates_with_one_full_wave(tmp_path):
    clock = FakeClock()
    api = FakeApiServer(clock=clock)
    _load(api)
    s = _sched(api, clock)
    s.run_cycle()
    save_scheduler(s, str(tmp_path))
    # Rewrite as a v4 file: no shard_map key existed before v5.
    state = json.load(open(tmp_path / "state.json"))
    state["version"] = 4
    state.pop("shard_map", None)
    json.dump(state, open(tmp_path / "state.json", "w"))

    clock2 = FakeClock(5000.0)
    api2 = FakeApiServer(clock=clock2)
    _load(api2)
    s2 = _sched(api2, clock2)
    assert restore_scheduler(s2, str(tmp_path)) is True
    # No map to adopt: the replica keeps its constructed K…
    assert s2.shard_set.num_shards == 4 and s2.shard_set.map_generation == 0
    # …and the restore escalates exactly the documented one full wave.
    s2.run_cycle()
    assert s2.delta.full_solve_reasons.get("restore", 0) >= 1


# -- vectorized reflector event fold (delta/index.py) ------------------------


def _mk_state(n_nodes=6):
    names = tuple(f"fn{i}" for i in range(n_nodes))
    return SolveState(
        node_names=names,
        node_sig=("sig",),
        res_vocab=("cpu", "memory"),
        res_scales=(1, 1),
        alloc64=np.full((n_nodes, 2), 10**12, dtype=np.int64),
        used64=np.zeros((n_nodes, 2), dtype=np.int64),
        row={nm: i for i, nm in enumerate(names)},
    )


def _seed_and_events(state, n=30):
    """Commit placements then build one unique-key event wave mixing
    deletes, re-pendings, rebinds, fresh binds, and a pending-carrier
    delete — deterministic, so two states seed identically."""
    names = state.node_names
    for i in range(n // 2):
        node = names[i % len(names)]
        pod = make_pod(f"old{i}", cpu="500m", memory="1Gi", node_name=node)
        state.commit(f"default/old{i}", node, req64_of(pod, state.res_vocab))
    state.unsched["default/old1"] = (False, None, None, False)
    events = []
    for i in range(n // 2):
        prev = make_pod(f"old{i}", node_name=names[i % len(names)])
        if i % 3 == 0:  # watch DELETE of a committed placement
            events.append((("default", f"old{i}"), prev, None))
        elif i % 3 == 1:  # bound -> pending (deschedule)
            events.append((("default", f"old{i}"), prev, make_pod(f"old{i}")))
        else:  # out-of-band rebind to another node
            other = names[(i + 1) % len(names)]
            events.append((("default", f"old{i}"), prev, make_pod(f"old{i}", node_name=other)))
    for i in range(n - n // 2):
        node = names[(i * 3) % len(names)]
        events.append(
            (("default", f"new{i}"), None, make_pod(f"new{i}", cpu="250m", memory="512Mi", node_name=node))
        )
    # A pending pod vanishing: zero capacity change, carrier_deleted set.
    events.append((("default", "ghost"), make_pod("ghost"), None))
    return events


def test_vectorized_fold_matches_scalar_bit_for_bit():
    fast, slow = _mk_state(), _mk_state()
    ev_fast, ev_slow = _seed_and_events(fast), _seed_and_events(slow)
    assert len(ev_fast) >= 8 and len({k for k, _p, _n in ev_fast}) == len(ev_fast)
    out_fast = DeltaIndex().fold(fast, ev_fast)
    out_slow = DeltaIndex()._fold_scalar(slow, ev_slow)
    # int64 scatter adds are exact and order-free: bit-identical tensors.
    assert (fast.used64 == slow.used64).all()
    assert set(fast.placements) == set(slow.placements)
    for pf, ent in fast.placements.items():
        other = slow.placements[pf]
        assert ent[0] == other[0] and ent[1] == other[1] and (ent[2] == other[2]).all()
    assert fast.unsched == slow.unsched
    # The FoldResult verdict matches field for field.
    assert out_fast.ok == out_slow.ok is True
    assert out_fast.freed_nodes == out_slow.freed_nodes
    assert out_fast.freed_unknown == out_slow.freed_unknown
    assert out_fast.carrier_deleted == out_slow.carrier_deleted is True
    assert out_fast.dirty == out_slow.dirty


def test_fold_dispatch_fast_path_vs_fallbacks(monkeypatch):
    calls = []
    orig = DeltaIndex._fold_scalar
    monkeypatch.setattr(
        DeltaIndex, "_fold_scalar", lambda self, st, ev: calls.append(len(ev)) or orig(self, st, ev)
    )
    st = _mk_state()
    events = _seed_and_events(st, n=20)
    out = DeltaIndex().fold(st, events)
    assert out.ok and not calls  # unique keys, >= 8 events: vectorized path
    # Duplicate keys fall back to the order-dependent scalar loop.
    st2 = _mk_state()
    ev2 = _seed_and_events(st2, n=20)
    DeltaIndex().fold(st2, ev2 + [ev2[0]])
    assert calls == [len(ev2) + 1]
    # Small waves take the scalar loop directly.
    calls.clear()
    st3 = _mk_state()
    DeltaIndex().fold(st3, _seed_and_events(st3, n=4)[:3])
    assert len(calls) == 1


def test_vectorized_fold_microbench():
    """The batch fold must not lose to the scalar loop on a large unique-key
    wave (generous 1.5x margin absorbs timer noise; the dispatch test above
    pins that the fast path actually runs)."""
    n = 3000
    best = {"fast": float("inf"), "slow": float("inf")}
    for _ in range(3):
        for label, fn in (("fast", DeltaIndex.fold), ("slow", DeltaIndex._fold_scalar)):
            st = _mk_state(64)
            events = [
                (
                    ("default", f"p{i}"),
                    None,
                    make_pod(f"p{i}", cpu="250m", memory="512Mi", node_name=st.node_names[i % 64]),
                )
                for i in range(n)
            ]
            idx = DeltaIndex()
            t0 = time.perf_counter()
            out = fn(idx, st, events)
            best[label] = min(best[label], time.perf_counter() - t0)
            assert out.ok and len(st.placements) == n
    assert best["fast"] <= best["slow"] * 1.5, best
