"""Topology-aware gang placement (ISSUE 6): the interconnect distance model,
the fused rank-aware locality term, backend parity, the sim scenarios'
locality verdict, and the observability surface.

The ISSUE acceptance criterion is pinned here: on `slice-fragmented-cluster`
topology-aware scoring places EVERY feasible gang with zero cross-rack edges
where a single-rack fit exists, while the topology-blind baseline does not —
asserted through the scorecard `locality` block.
"""

import json
import random
import urllib.request
from dataclasses import replace

import numpy as np
import pytest

from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.core.snapshot import ClusterSnapshot
from tpu_scheduler.models.profiles import DEFAULT_PROFILE, PROFILES
from tpu_scheduler.ops.pack import pack_snapshot
from tpu_scheduler.testing import make_node, make_pod
from tpu_scheduler.topology.locality import (
    gang_placement_stats,
    gang_state_update,
    gang_topology_term,
    pack_topology,
)
from tpu_scheduler.topology.model import DEFAULT_LEVEL_KEYS, TopologyModel, load_topology_file

SLICE_KEY = DEFAULT_LEVEL_KEYS[0][1]
RACK_KEY = DEFAULT_LEVEL_KEYS[1][1]


def topo_node(i: int, cpu="8", memory="32Gi", slice_size=3, rack_size=6):
    return make_node(
        f"n{i:02d}",
        cpu=cpu,
        memory=memory,
        labels={SLICE_KEY: f"s{i // slice_size}", RACK_KEY: f"r{i // rack_size}", "name": f"n{i:02d}"},
    )


def build_topo_cluster(n_nodes=24, gangs=2, gang_size=4, fillers=6, cpu="8"):
    nodes = [topo_node(i, cpu=cpu) for i in range(n_nodes)]
    pods = []
    for g in range(gangs):
        for m in range(gang_size):
            pods.append(make_pod(f"g{g}-m{m}", cpu="2", memory="4Gi", gang=f"gang-{g}"))
    for f in range(fillers):
        pods.append(make_pod(f"f{f}", cpu="1", memory="2Gi"))
    snap = ClusterSnapshot.build(nodes, pods)
    compiled = TopologyModel.detect(nodes).compile(nodes)
    packed = pack_snapshot(snap)
    topo = pack_topology(compiled, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes)
    return snap, compiled, packed, topo


# --- model ------------------------------------------------------------------


def test_detect_compile_and_distance_matrix():
    nodes = [topo_node(i) for i in range(12)]
    model = TopologyModel.detect(nodes)
    assert [lv.name for lv in model.levels] == ["slice", "rack"]
    compiled = model.compile(nodes)
    dm = compiled.distance_matrix()
    assert dm.shape == (12, 12) and np.allclose(dm, dm.T) and (np.diag(dm) == 0).all()
    assert dm[0, 1] == 0.0  # same slice
    assert dm[0, 3] == 1.0  # same rack, different slice
    assert dm[0, 7] == 2.0  # different rack
    assert compiled.domains_of("n00") == ("s0", "r0")
    assert compiled.domains_of("ghost") is None


def test_detect_none_on_unlabeled_cluster_and_singleton_fallback():
    assert TopologyModel.detect([make_node("plain")]) is None
    # A rack-only cluster compiles to one level; an unlabeled straggler in a
    # labeled cluster gets a singleton domain (maximally far).
    nodes = [
        make_node("a", labels={RACK_KEY: "r0"}),
        make_node("b", labels={RACK_KEY: "r0"}),
        make_node("c", labels={}),
    ]
    model = TopologyModel.detect(nodes)
    assert [lv.name for lv in model.levels] == ["rack"]
    dm = model.compile(nodes).distance_matrix()
    assert dm[0, 1] == 0.0 and dm[0, 2] == 1.0


def test_topology_file_spec_roundtrip(tmp_path):
    spec = {
        "levels": [{"name": "slice", "distance": 1.0}, {"name": "rack", "distance": 2.5}],
        "nodes": {"a": {"slice": "s0", "rack": "r0"}, "b": {"slice": "s1", "rack": "r0"}},
    }
    path = tmp_path / "topo.json"
    path.write_text(json.dumps(spec))
    model = load_topology_file(str(path))
    compiled = model.compile([make_node("a"), make_node("b")])
    dm = compiled.distance_matrix()
    assert dm[0, 1] == 1.0  # slice differs, rack shared
    assert list(compiled.level_distances()) == [1.0, 2.5]
    with pytest.raises(ValueError):
        TopologyModel.from_spec({"levels": []})


# --- locality term ----------------------------------------------------------


def test_pack_topology_gang_ids_and_gangless_none():
    snap, compiled, packed, topo = build_topo_cluster()
    ids = topo.pod_gang_id
    assert topo.gang_names == ("gang-0", "gang-1")
    assert list(ids[:8]) == [1, 1, 1, 1, 2, 2, 2, 2]
    assert (ids[8:] == 0).all()  # fillers + padding ride the zero row
    plain = ClusterSnapshot.build(snap.nodes, [make_pod("solo")])
    p2 = pack_snapshot(plain)
    assert pack_topology(compiled, plain.pending_pods(), p2.padded_pods, p2.node_names, p2.padded_nodes) is None


def test_anchor_term_matches_distance_matrix_factoring():
    """The per-level one-hot factoring in gang_topology_term must equal the
    direct gang_nodes @ distance_matrix product — the algebraic identity
    that lets the device path skip the [N, N] tensor."""
    snap, compiled, packed, topo = build_topo_cluster()
    n_pad = packed.padded_nodes
    g1 = topo.n_gangs + 1
    rng = np.random.RandomState(0)
    gang_nodes = np.zeros((g1, n_pad + 1), dtype=np.float32)
    gang_nodes[1:, : len(compiled.node_names)] = rng.randint(0, 3, size=(g1 - 1, len(compiled.node_names)))
    avail = packed.node_avail
    # Zero-demand pods: the fit bonus applies everywhere equally per level;
    # isolate the anchor by differencing against a zero-placement call.
    no_place = np.zeros_like(gang_nodes)
    req = np.zeros_like(packed.pod_req)
    active = np.zeros((packed.padded_pods,), dtype=bool)
    t_placed = gang_topology_term(np, gang_nodes, topo.meta, avail, topo.pod_gang_id, req, active, np.float32(1.0))
    t_empty = gang_topology_term(np, no_place, topo.meta, avail, topo.pod_gang_id, req, active, np.float32(1.0))
    anchor = t_placed - t_empty  # fit/herd cancel; −ANCHOR_SCALE·Σ counts·dist remains
    from tpu_scheduler.topology.locality import ANCHOR_SCALE

    n_real = len(compiled.node_names)
    dm = compiled.distance_matrix()
    expect = -ANCHOR_SCALE * (gang_nodes[:, :n_real] @ dm)
    assert np.allclose(anchor[:, :n_real], expect, atol=1e-3)
    assert (t_placed[0] == 0).all()  # the no-gang row is pinned to zero


def test_gang_state_update_sentinels():
    gang_nodes = np.zeros((3, 5), dtype=np.float32)  # 2 gangs, 4 nodes + sentinel
    accepted = np.array([True, False, True, True])
    choice = np.array([1, 4, 4, 2], dtype=np.int32)  # 4 = non-claimant sentinel
    gang_id = np.array([1, 1, 2, 0], dtype=np.int32)  # last pod gangless
    out = gang_state_update(np, gang_nodes, accepted, choice, gang_id)
    assert out[1, 1] == 1.0  # accepted member counted
    assert out[1, 4] == 0.0 and out[2, 4] == 1.0  # sentinel column absorbs, never read
    assert out[0, 2] == 1.0  # gangless row absorbs, never read
    assert (gang_nodes == 0).all()  # numpy path copies


def test_gang_placement_stats():
    doms = [("s0", "r0"), ("s0", "r0"), ("s1", "r0"), ("s4", "r2")]
    stats = gang_placement_stats(doms, [1.0, 1.0])
    assert stats["members"] == 4 and stats["pairs"] == 6
    assert stats["max_distance"] == 2.0
    assert stats["cross_edges"] == 3  # every pair involving the r2 member
    one_slice = gang_placement_stats([("s0", "r0")] * 3, [1.0, 1.0])
    assert one_slice["max_distance"] == 0.0 and one_slice["cross_edges"] == 0


# --- placement behaviour + backend parity -----------------------------------


def test_gangs_converge_to_one_slice_and_blind_baseline_scatters():
    snap, compiled, packed, topo = build_topo_cluster()
    packed_t = replace(packed, topology=topo)
    nb = NativeBackend()
    r = nb.schedule(packed_t, DEFAULT_PROFILE)
    dists = compiled.level_distances()
    for g in ("g0", "g1"):
        doms = [compiled.domains_of(n) for pf, n in r.bindings if pf.startswith(f"default/{g}-")]
        assert len(doms) == 4
        assert gang_placement_stats(doms, dists)["max_distance"] == 0.0, g
    r_blind = nb.schedule(packed, DEFAULT_PROFILE)
    blind_worst = 0.0
    for g in ("g0", "g1"):
        doms = [compiled.domains_of(n) for pf, n in r_blind.bindings if pf.startswith(f"default/{g}-")]
        blind_worst = max(blind_worst, gang_placement_stats(doms, dists)["max_distance"])
    assert blind_worst > 0.0  # jitter scatters near-ties without the term


def test_native_tpu_parity_with_topology_both_drivers():
    """ISSUE satellite: identical placements and locality scores for a
    seeded gang workload on both backends (and both auction drivers)."""
    from tpu_scheduler.backends.tpu import TpuBackend

    rng = random.Random(7)
    nodes = [topo_node(i, cpu=str(rng.choice([8, 16, 32])), slice_size=4, rack_size=8) for i in range(32)]
    pods = []
    gi = 0
    for a in range(40):
        if rng.random() < 0.4:
            for m in range(rng.randrange(2, 6)):
                pods.append(
                    make_pod(f"g{gi}-m{m}", cpu=f"{rng.choice([500, 1000, 2000])}m", memory="2Gi",
                             gang=f"gang-{gi}", priority=rng.choice([0, 5]))
                )
            gi += 1
        else:
            pods.append(make_pod(f"p{a}", cpu=f"{rng.choice([250, 500, 1000])}m", memory="1Gi"))
    snap = ClusterSnapshot.build(nodes, pods)
    compiled = TopologyModel.detect(nodes).compile(nodes)
    packed = pack_snapshot(snap)
    topo = pack_topology(compiled, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes)
    packed = replace(packed, topology=topo)
    tb = TpuBackend(use_pallas=False)
    for profile in (DEFAULT_PROFILE, PROFILES["throughput"], DEFAULT_PROFILE.with_(driver="epochs")):
        rn = NativeBackend().schedule(packed, profile)
        rt = tb.schedule(packed, profile)
        assert rn.bindings == rt.bindings, profile.name
        assert rn.unschedulable == rt.unschedulable
        # identical placements → identical locality scores, asserted explicitly
        dists = compiled.level_distances()
        for g in range(gi):
            dn = [compiled.domains_of(n) for pf, n in rn.bindings if pf.startswith(f"default/g{g}-")]
            dt = [compiled.domains_of(n) for pf, n in rt.bindings if pf.startswith(f"default/g{g}-")]
            if len(dn) >= 2:
                assert gang_placement_stats(dn, dists) == gang_placement_stats(dt, dists)


def test_chaos_trace_replay_parity_on_topology_scenario(tmp_path):
    """Extend the chaos-trace parity pattern: one recorded topology-scenario
    trace replayed on native AND TpuBackend-on-CPU must fingerprint-match."""
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.sim import run_scenario

    path = str(tmp_path / "topo-trace.jsonl")
    live = run_scenario("slice-fragmented-cluster", seed=3, record=path)
    native = run_scenario(None, replay=path)
    jax_card = run_scenario(None, replay=path, backend=TpuBackend(use_pallas=False))
    fps = {live["fingerprint"], native["fingerprint"], jax_card["fingerprint"]}
    assert len(fps) == 1, fps
    assert native["locality"] == live["locality"] == jax_card["locality"]


# --- controller quality backstop --------------------------------------------


def test_cross_rack_rejects_only_when_single_rack_fit_existed():
    from tpu_scheduler.backends.base import CycleResult
    from tpu_scheduler.runtime.controller import Scheduler

    snap, compiled, packed, topo = build_topo_cluster(n_nodes=12, gangs=1, gang_size=2, fillers=0)
    packed = replace(packed, topology=topo)
    members = {"gang-0": {"default/g0-m0", "default/g0-m1"}}
    local = set(members["gang-0"])

    def result_for(nodes_chosen):
        bindings = list(zip(sorted(local), nodes_chosen))
        return CycleResult(assigned=np.zeros(2, np.int32), bindings=bindings, unschedulable=[], rounds=1)

    # Cross-rack placement while rack fits exist -> rejected for quality.
    rej = Scheduler._cross_rack_rejects(packed, result_for(["n00", "n07"]), members, local, set())
    assert rej == {"gang-0"}
    # Single-rack placement -> clean.
    assert Scheduler._cross_rack_rejects(packed, result_for(["n00", "n01"]), members, local, set()) == set()
    # Cross-rack but NO rack could fit the gang whole -> stands (best available).
    starved = replace(packed, node_avail=np.zeros_like(packed.node_avail), topology=topo)
    assert Scheduler._cross_rack_rejects(starved, result_for(["n00", "n07"]), members, local, set()) == set()


# --- the ISSUE acceptance scenario ------------------------------------------


def test_slice_fragmented_cluster_zero_cross_rack_vs_blind_baseline():
    """ISSUE acceptance: topology-aware scoring admits EVERY gang with zero
    cross-rack edges on slice-fragmented-cluster (scorecard-gated), while
    the topology-BLIND baseline does not."""
    from tpu_scheduler.sim import run_scenario
    from tpu_scheduler.sim.scorecard import SCORECARD_FIELDS

    card = run_scenario("slice-fragmented-cluster", seed=0)
    assert tuple(card) == SCORECARD_FIELDS
    loc = card["locality"]
    assert loc["enabled"] and loc["required"] and loc["levels"] == ["slice", "rack"]
    assert loc["gangs_scored"] > 50  # the workload really is gang-heavy
    assert loc["cross_rack_gangs"] == 0 and loc["cross_rack_edges"] == 0
    assert card["pass"], json.dumps(loc)
    assert card["pods"]["lost"] == 0 and card["pods"]["double_bound"] == 0

    blind = run_scenario("slice-fragmented-cluster", seed=0, topology=None)
    bloc = blind["locality"]
    assert bloc["cross_rack_gangs"] > 0  # the baseline scatters...
    assert not blind["pass"]  # ...and the locality gate fails it


def test_locality_gate_is_virtual_and_deterministic():
    from tpu_scheduler.sim import run_scenario

    c1 = run_scenario("slice-fragmented-cluster", seed=1)
    c2 = run_scenario("slice-fragmented-cluster", seed=1)
    assert json.dumps(c1, sort_keys=True) == json.dumps(c2, sort_keys=True)
    assert c1["pass"] and c1["locality"]["cross_rack_gangs"] == 0


def test_rack_failure_scenario_survives_with_invariants():
    """A whole rack dies mid-admission: no pods lost, invariants hold,
    churn-disturbed gangs are counted-and-skipped by the locality verdict,
    and the surviving admissions stay single-rack."""
    from tpu_scheduler.sim import run_scenario

    for seed in (0, 1):
        card = run_scenario("rack-failure-during-gang-admission", seed=seed)
        assert card["pass"], json.dumps(card["invariants"])
        assert card["pods"]["lost"] == 0 and card["pods"]["double_bound"] == 0
        assert card["pods"]["churn_recreated"] > 0  # the rack really died
        loc = card["locality"]
        assert loc["enabled"] and loc["levels"] == ["rack"]
        assert loc["cross_rack_gangs"] == 0


def test_new_scenarios_record_replay_bit_identical(tmp_path):
    """ISSUE satellite: record→replay bit-identity for both new scenarios
    across seeds {0, 1}."""
    from tpu_scheduler.sim import run_scenario

    for name in ("slice-fragmented-cluster", "rack-failure-during-gang-admission"):
        for seed in (0, 1):
            path = str(tmp_path / f"{name}-{seed}.jsonl")
            live = run_scenario(name, seed=seed, record=path)
            replayed = run_scenario(None, replay=path)
            assert replayed["fingerprint"] == live["fingerprint"], (name, seed)
            assert replayed["locality"] == live["locality"]


# --- observability ----------------------------------------------------------


def test_gang_distance_histogram_and_debug_locality_route():
    from tpu_scheduler.runtime.controller import Scheduler
    from tpu_scheduler.runtime.fake_api import FakeApiServer
    from tpu_scheduler.runtime.http_api import HttpApiServer

    api = FakeApiServer()
    nodes = [topo_node(i) for i in range(12)]
    pods = [make_pod(f"g0-m{m}", cpu="2", memory="4Gi", gang="gang-0") for m in range(3)]
    pods.append(make_pod("solo", cpu="1"))
    api.load(nodes=nodes, pods=pods)
    sched = Scheduler(api, NativeBackend())
    sched.run(until_settled=True)
    snap = sched.metrics.snapshot()
    assert snap.get("scheduler_topology_cycles_total", 0) >= 1
    assert snap.get("scheduler_gangs_admitted_total", 0) == 1
    text = sched.metrics.to_prometheus()
    assert "scheduler_gang_placement_distance_bucket" in text
    assert 'scheduler_gang_placement_distance_bucket{le="0"} 1' in text  # one slice-local gang

    server = HttpApiServer(api, metrics=sched.metrics, recorder=sched.recorder).start()
    try:
        with urllib.request.urlopen(f"{server.base_url}/debug/pods/default/g0-m0") as r:
            d = json.load(r)
    finally:
        server.stop()
    loc = d["locality"]
    assert loc["gang"] == "gang-0" and loc["members"] == 3 and loc["members_bound"] == 3
    assert loc["stats"]["max_distance"] == 0.0 and loc["stats"]["cross_edges"] == 0
    assert loc["stats"]["levels"] == ["slice", "rack"]
    # the admitted-gang timeline carries the locality verdict
    timeline = d["timeline"]
    admitted = [e for e in timeline if e["kind"] == "gang-admitted"]
    assert admitted and "max_dist=0.0" in admitted[-1]["detail"]


def test_no_topology_attach_for_gangless_or_unlabeled_clusters():
    from tpu_scheduler.runtime.controller import Scheduler
    from tpu_scheduler.runtime.fake_api import FakeApiServer

    # Labeled cluster, no gangs: zero topology cycles, zero overhead.
    api = FakeApiServer()
    api.load(nodes=[topo_node(i) for i in range(6)], pods=[make_pod("a"), make_pod("b")])
    sched = Scheduler(api, NativeBackend())
    sched.run(until_settled=True)
    assert sched.metrics.snapshot().get("scheduler_topology_cycles_total", 0) == 0
    # Unlabeled cluster with gangs: auto-detect declines, cycle still binds.
    api2 = FakeApiServer()
    api2.load(
        nodes=[make_node("p1", cpu=8), make_node("p2", cpu=8)],
        pods=[make_pod(f"g-{m}", gang="g") for m in range(2)],
    )
    sched2 = Scheduler(api2, NativeBackend())
    sched2.run(until_settled=True)
    assert sched2.metrics.snapshot().get("scheduler_topology_cycles_total", 0) == 0
    assert sched2.metrics.snapshot().get("scheduler_gangs_admitted_total", 0) == 1
