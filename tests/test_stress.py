"""Kitchen-sink stress: randomized clusters exercising EVERY feature at once
(selectors, taints, affinity, anti-affinity, hard+soft spread, soft scoring,
gangs, pools, priorities) through the full controller across backends and
modes, checked against the framework's global invariants:

  I1 capacity    — no node oversubscribed under the exact scalar arithmetic
  I2 predicates  — every placement passes the full scalar chain vs the final
                   state minus itself (order-free necessary condition)
  I3 gangs       — every gang fully placed or fully pending
  I4 selectors   — every placement honors nodeSelector / hard taints /
                   required affinity (subsumed by I2, kept for cheap triage)
"""

import pytest

import tpu_scheduler.core.predicates as P
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.backends.tpu import TpuBackend
from tpu_scheduler.core.snapshot import ClusterSnapshot, node_allocatable, node_used_resources
from tpu_scheduler.models.profiles import DEFAULT_PROFILE
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import synth_cluster


def _kitchen_sink(seed):
    return synth_cluster(
        n_nodes=40,
        n_pending=240,
        n_bound=80,
        seed=seed,
        selector_fraction=0.25,
        multi_container_fraction=0.15,
        anti_affinity_fraction=0.12,
        spread_fraction=0.12,
        tainted_fraction=0.2,
        cordoned_fraction=0.05,
        node_affinity_fraction=0.15,
        soft_taint_fraction=0.2,
        preferred_affinity_fraction=0.2,
        schedule_anyway_fraction=0.12,
        gang_fraction=0.12,
        pod_affinity_fraction=0.1,
        preferred_pod_affinity_fraction=0.15,
        extended_fraction=0.15,
    )


def _check_invariants(api, snap0):
    final = ClusterSnapshot.build(api.list_nodes(), api.list_pods())
    node_by = {n.name: n for n in final.nodes}
    # I1: capacity exact
    for n in final.nodes:
        used = node_used_resources(final, n.name)
        alloc = node_allocatable(n)
        assert used.cpu <= alloc.cpu and used.memory <= alloc.memory, f"{n.name} oversubscribed"
    # I2: every placement THE SCHEDULER made passes the order-free part of
    # the scalar chain vs the final state minus itself (pre-bound pods come
    # from the generator, which round-robins without predicates).  Topology
    # spread is deliberately EXCLUDED here: it is order-dependent — a pod
    # matching a constraint's selector but not declaring it may legally land
    # in the domain later and raise the count past the skew a declarer saw
    # at its own (valid) turn.  Spread validity is covered by the
    # per-cycle acceptance-order certificate in test_constraints_tensor.py.
    scheduled_names = {p.metadata.name for p in snap0.pending_pods()}
    order_free = [
        (r, pred) for r, pred in P.PREDICATE_CHAIN if r != P.InvalidNodeReason.TOPOLOGY_SPREAD_VIOLATION
    ]
    for pod, node in final.placed_pods():
        if pod.metadata.name not in scheduled_names:
            continue
        others = ClusterSnapshot.build(final.nodes, [q for q in final.pods if q is not pod])
        for reason, pred in order_free:
            assert pred(pod, node_by[node.name], others), f"{pod.metadata.name} on {node.name}: {reason}"
    # I3: gang atomicity
    placed_names = {p.metadata.name for p in final.pods if p.spec is not None and p.spec.node_name}
    gangs: dict[str, list[str]] = {}
    for p in snap0.pending_pods():
        if p.spec is not None and p.spec.gang:
            gangs.setdefault(p.spec.gang, []).append(p.metadata.name)
    for g, members in gangs.items():
        n_placed = sum(1 for m in members if m in placed_names)
        assert n_placed in (0, len(members)), f"gang {g}: {n_placed}/{len(members)} placed"
    return final


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_kitchen_sink_batch_native(seed):
    snap = _kitchen_sink(seed)
    api = FakeApiServer()
    api.load(snap.nodes, snap.pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    sched.run(until_settled=True, max_cycles=10)
    final = _check_invariants(api, snap)
    # the bulk must schedule (sanity against everything being rejected)
    assert sum(1 for p in final.pods if p.spec is not None and p.spec.node_name) > 200


@pytest.mark.parametrize("seed", [4, 5])
def test_kitchen_sink_tpu_pipelined(seed):
    snap = _kitchen_sink(seed)
    api = FakeApiServer()
    api.load(snap.nodes, snap.pods)
    sched = Scheduler(api, TpuBackend(), fallback_backend=NativeBackend(), requeue_seconds=0.0, pipeline=True)
    sched.run(until_settled=True, max_cycles=10)
    sched.close()
    _check_invariants(api, snap)


def test_kitchen_sink_preemption_waves():
    """Low-priority fill, then a high-priority wave with preemption on: the
    invariants must hold through evictions."""
    snap = _kitchen_sink(6)
    api = FakeApiServer()
    api.load(snap.nodes, snap.pods)
    profile = DEFAULT_PROFILE.with_(preemption=True)
    sched = Scheduler(api, NativeBackend(), profile=profile, requeue_seconds=0.0)
    sched.run(until_settled=True, max_cycles=8)
    from tpu_scheduler.testing import make_pod

    for i in range(30):
        api.create_pod(make_pod(f"vip-{i}", cpu="2", memory="4Gi", priority=50))
    sched.run(until_settled=True, max_cycles=8)
    final = _check_invariants(api, snap)
    vips_placed = sum(1 for p in final.pods if p.metadata.name.startswith("vip") and p.spec.node_name)
    assert vips_placed >= 20  # preemption made room for most of the wave


def test_chaos_cycles_hold_invariants():
    """Multi-cycle chaos: new pods arriving, cordon/taint toggling, priority
    preemption with a PodDisruptionBudget in play (no NoExecute — taint
    evictions legitimately bypass budgets).  After every cycle: capacity
    exact, gang atomicity, and the PDB floor never breached by preemption."""
    import random

    from tpu_scheduler.api.objects import ObjectMeta, PodDisruptionBudget, Taint
    from tpu_scheduler.testing import make_node, make_pod

    rng = random.Random(7)
    api = FakeApiServer()
    nodes = [make_node(f"n{i}", cpu="8", memory="32Gi", labels={"zone": f"z{i % 3}", "name": f"n{i}"}) for i in range(12)]
    db = [make_pod(f"db-{i}", cpu="2", memory="2Gi", labels={"app": "db"}, priority=0) for i in range(6)]
    api.load(nodes=nodes, pods=db)
    api.create_pdb(
        PodDisruptionBudget(metadata=ObjectMeta(name="db", namespace="default"), match_labels={"app": "db"}, min_available=4)
    )
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0, profile=DEFAULT_PROFILE.with_(preemption=True))
    sched.run_cycle()
    assert sum(1 for p in api.list_pods() if p.metadata.name.startswith("db-") and p.spec.node_name) == 6

    seq = 0
    for cycle in range(12):
        # chaos: arrivals (some high-priority hogs that trigger preemption)
        for _ in range(rng.randrange(0, 5)):
            seq += 1
            prio = rng.choice([0, 1, 5, 50, 100])
            cpu = rng.choice(["500m", "1", "2", "6"])
            api.create_pod(make_pod(f"w{seq}", cpu=cpu, memory="1Gi", priority=prio))
        # chaos: cordon/uncordon + NoSchedule taint toggling
        from tpu_scheduler.api.objects import NodeSpec

        for n in api.list_nodes():
            if rng.random() < 0.1:
                if n.spec is None:
                    n.spec = NodeSpec()
                n.spec.unschedulable = not n.spec.unschedulable
            if rng.random() < 0.1:
                if n.spec is None:
                    n.spec = NodeSpec()
                n.spec.taints = [] if n.spec.taints else [Taint(key="flaky", value="1", effect="NoSchedule")]
        sched.run_cycle()
        snap = ClusterSnapshot.build(api.list_nodes(), api.list_pods())
        for n in snap.nodes:
            used = node_used_resources(snap, n.name)
            alloc = node_allocatable(n)
            assert used.cpu <= alloc.cpu and used.memory <= alloc.memory, f"cycle {cycle}: {n.name} oversubscribed"
        healthy_db = sum(1 for q, _ in snap.placed_pods() if (q.metadata.labels or {}).get("app") == "db")
        assert healthy_db >= 4, f"cycle {cycle}: PDB floor breached ({healthy_db} < 4)"
