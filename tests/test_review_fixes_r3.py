"""Regression tests for the third code-review round: shim 128-bit mantissa
wrap, affinity enforcement in the sample policy, fallback scoping."""

import random

import pytest

from conftest import ensure_native_shim
from tpu_scheduler.api.objects import PodAntiAffinityTerm, TopologySpreadConstraint
from tpu_scheduler.backends.native import NativeBackend
from tpu_scheduler.errors import BackendUnavailable
from tpu_scheduler.runtime.controller import Scheduler
from tpu_scheduler.runtime.fake_api import FakeApiServer
from tpu_scheduler.testing import make_node, make_pod


def test_shim_huge_mantissa_matches_python():
    """A 39-digit mantissa wraps unsigned __int128; the shim must saturate
    (and then clamp like the oracle), not return wrapped garbage."""
    from tpu_scheduler.api.quantity import cpu_to_millis, memory_to_bytes
    from tpu_scheduler.ops import native_ext

    ensure_native_shim()

    def clamp64(v):
        return max(-(2**63 - 1), min(2**63 - 1, v))

    cases = [
        "510423550381407695195061911147652317184e-24",  # wraps to >= mantissa
        "340282366920938463463374607431768211456",  # 2^128 exactly
        "99999999999999999999999999999999999999999e-30",
        "170141183460469231731687303715884105727e-20",
        "-510423550381407695195061911147652317184e-24",
        "1.00000000000000000000000000000000000000000001e2",
    ]
    for s in cases:
        assert native_ext.batch_parse([s], native_ext.MODE_CPU_MILLIS)[0] == clamp64(cpu_to_millis(s)), s
        assert native_ext.batch_parse([s], native_ext.MODE_MEM_BYTES)[0] == clamp64(memory_to_bytes(s)), s
    rows = native_ext.pack_requests(["99999999999999999999999999999999999999999e-30"], ["2Gi"])
    assert rows[0, 0] == min(2**31 - 1, clamp64(cpu_to_millis("99999999999999999999999999999999999999999e-30")))
    assert rows[0, 1] == 2 * 1024 * 1024


def zone_api():
    api = FakeApiServer()
    api.create_node(make_node("n0", cpu="16", memory="64Gi", labels={"zone": "a"}))
    api.create_node(make_node("n1", cpu="16", memory="64Gi", labels={"zone": "a"}))
    api.create_node(make_node("n2", cpu="16", memory="64Gi", labels={"zone": "b"}))
    return api


def test_sample_policy_enforces_anti_affinity():
    api = zone_api()
    api.create_pod(make_pod("web-0", labels={"app": "web"}, node_name="n0", phase="Running"))
    api.create_pod(
        make_pod(
            "web-1",
            labels={"app": "web"},
            anti_affinity=[PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")],
        )
    )
    sched = Scheduler(api, NativeBackend(), policy="sample", rng=random.Random(0), attempts=50)
    m = sched.run_cycle()
    assert m.bound == 1
    bound = [p for p in api.list_pods() if p.metadata.name == "web-1"]
    assert bound[0].spec.node_name == "n2"  # only zone b is legal


def test_sample_policy_enforces_anti_affinity_between_cycle_peers():
    # Two pending peers with mutual anti-affinity: the second must see the
    # first's same-cycle placement via the overlay and avoid its zone.
    api = zone_api()
    term = [PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")]
    api.create_pod(make_pod("web-a", labels={"app": "web"}, anti_affinity=term))
    api.create_pod(make_pod("web-b", labels={"app": "web"}, anti_affinity=term))
    sched = Scheduler(api, NativeBackend(), policy="sample", rng=random.Random(0), attempts=100)
    sched.run_cycle()
    zones = {}
    for p in api.list_pods():
        if p.spec.node_name is not None:
            zones[p.metadata.name] = {"n0": "a", "n1": "a", "n2": "b"}[p.spec.node_name]
    assert len(zones) == 2
    assert zones["web-a"] != zones["web-b"]


def test_sample_policy_enforces_topology_spread():
    api = zone_api()
    api.create_pod(make_pod("w0", labels={"app": "web"}, node_name="n0", phase="Running"))
    api.create_pod(make_pod("w1", labels={"app": "web"}, node_name="n1", phase="Running"))
    api.create_pod(
        make_pod(
            "w2",
            labels={"app": "web"},
            topology_spread=[TopologySpreadConstraint(topology_key="zone", max_skew=1, match_labels={"app": "web"})],
        )
    )
    sched = Scheduler(api, NativeBackend(), policy="sample", rng=random.Random(0), attempts=50)
    m = sched.run_cycle()
    assert m.bound == 1
    w2 = [p for p in api.list_pods() if p.metadata.name == "w2"][0]
    assert w2.spec.node_name == "n2"  # zone a would give skew 3 > 1


class BuggyBackend(NativeBackend):
    name = "buggy"

    def assign(self, packed, profile):
        raise TypeError("programming error, not a device failure")


def test_programming_errors_do_not_trigger_fallback():
    api = zone_api()
    api.create_pod(make_pod("p", cpu="1", memory="1Gi"))
    sched = Scheduler(api, BuggyBackend(), fallback_backend=NativeBackend())
    with pytest.raises(TypeError):
        sched.run_cycle()


class UnavailableBackend(NativeBackend):
    name = "unavailable"

    def assign(self, packed, profile):
        raise BackendUnavailable("device lost")


def test_unavailability_still_falls_back():
    api = zone_api()
    api.create_pod(make_pod("p", cpu="1", memory="1Gi"))
    sched = Scheduler(api, UnavailableBackend(), fallback_backend=NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 1


def test_batch_policy_enforces_anti_affinity():
    # The default batch policy must hold affinity-constrained pods out of the
    # tensor pass and schedule them through the exact sequential chain.
    api = zone_api()
    api.create_pod(make_pod("web-0", labels={"app": "web"}, node_name="n0", phase="Running"))
    api.create_pod(
        make_pod(
            "web-1",
            labels={"app": "web"},
            anti_affinity=[PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")],
        )
    )
    api.create_pod(make_pod("plain", cpu="1", memory="1Gi"))
    sched = Scheduler(api, NativeBackend())  # policy="batch" default
    m = sched.run_cycle()
    assert m.bound == 2
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods() if p.spec.node_name}
    assert placed["web-1"] == "n2"  # zones a (n0, n1) are forbidden


def test_batch_policy_direction_b_holds_back_plain_pod():
    # A plain pod matched by a *placed* pod's term must go through the chain.
    api = zone_api()
    api.create_pod(
        make_pod(
            "guard",
            labels={"app": "web"},
            node_name="n0",
            phase="Running",
            anti_affinity=[PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")],
        )
    )
    api.create_pod(make_pod("web-1", labels={"app": "web"}))
    sched = Scheduler(api, NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 1
    placed = {p.metadata.name: p.spec.node_name for p in api.list_pods() if p.spec.node_name}
    assert placed["web-1"] == "n2"


def test_batch_policy_enforces_topology_spread():
    api = zone_api()
    api.create_pod(make_pod("w0", labels={"app": "web"}, node_name="n0", phase="Running"))
    api.create_pod(make_pod("w1", labels={"app": "web"}, node_name="n1", phase="Running"))
    api.create_pod(
        make_pod(
            "w2",
            labels={"app": "web"},
            topology_spread=[TopologySpreadConstraint(topology_key="zone", max_skew=1, match_labels={"app": "web"})],
        )
    )
    sched = Scheduler(api, NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 1
    w2 = [p for p in api.list_pods() if p.metadata.name == "w2"][0]
    assert w2.spec.node_name == "n2"


def test_batch_policy_anti_affine_peers_spread_out():
    # Two pending peers with mutual anti-affinity in one batch cycle: the
    # sequential phase sees the first one's commitment via the overlay.
    api = zone_api()
    term = [PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")]
    api.create_pod(make_pod("web-a", labels={"app": "web"}, anti_affinity=term))
    api.create_pod(make_pod("web-b", labels={"app": "web"}, anti_affinity=term))
    sched = Scheduler(api, NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 2
    zmap = {"n0": "a", "n1": "a", "n2": "b"}
    zones = [zmap[p.spec.node_name] for p in api.list_pods() if p.spec.node_name]
    assert sorted(zones) == ["a", "b"]


def test_batch_policy_unschedulable_constrained_pod_requeues():
    api = zone_api()
    term = [PodAntiAffinityTerm(match_labels={"app": "web"}, topology_key="zone")]
    for name, zone_node in [("w-a", "n0"), ("w-b", "n2")]:
        api.create_pod(make_pod(name, labels={"app": "web"}, node_name=zone_node, phase="Running"))
    api.create_pod(make_pod("w-c", labels={"app": "web"}, anti_affinity=term))
    sched = Scheduler(api, NativeBackend())
    m = sched.run_cycle()
    assert m.bound == 0 and m.unschedulable == 1
    assert "default/w-c" in sched.requeue_at
