// tpu_scheduler native packing shim.
//
// C++ implementation of the Kubernetes quantity grammar and batch request
// packing — the native-code equivalent of the reference's kube_quantity
// dependency (reference: src/util.rs:17-36 uses kube_quantity::ParsedQuantity
// for all resource arithmetic).  Python's parser (api/quantity.py) stays the
// semantic oracle; this shim must agree exactly (tests/test_native_ext.py
// fuzzes them against each other) and exists to take quantity parsing off
// the host hot path when packing large snapshots.
//
// Exact integer arithmetic: value = sign * mantissa * 10^dec_exp * 2^bin_exp,
// evaluated with __int128 saturating multiplies, then ceil-divided to the
// target unit (cpu -> millicores, memory -> bytes).  Results clamp to int64;
// the tensor layer clamps further to int32 (ops/pack.py).
//
// Build: `make -C native` -> libtpusched.so, loaded via ctypes
// (tpu_scheduler/ops/native_ext.py).

#include <cstdint>
#include <cstring>
#include <cctype>

namespace {

const __int128 I128_MAX_SENTINEL = (((__int128)1) << 126);  // saturation rail
const int64_t I64_MAX = INT64_MAX;

// Saturating non-negative __int128 multiply.
static __int128 mul_sat(__int128 a, __int128 b) {
    if (a == 0 || b == 0) return 0;
    if (a >= I128_MAX_SENTINEL / b) return I128_MAX_SENTINEL;
    return a * b;
}

static __int128 pow_sat(__int128 base, int exp) {
    __int128 r = 1;
    for (int i = 0; i < exp; i++) {
        r = mul_sat(r, base);
        if (r >= I128_MAX_SENTINEL) return I128_MAX_SENTINEL;
    }
    return r;
}

struct Parsed {
    bool ok;
    bool negative;
    unsigned __int128 mantissa;  // digits with the dot removed (saturating)
    int dec_exp;                 // power of ten (fraction digits + suffix/exponent)
    int bin_exp;                 // power of two (binary SI suffixes)
    bool inexact;                // saturation dropped digits: result is approximate
};

// Python's parse_quantity does s.strip(): allow any surrounding whitespace.
static bool at_end(const char* c) {
    while (isspace((unsigned char)*c)) c++;
    return *c == '\0';
}

// Grammar: sign? digits ('.' digits?)? (suffix | [eE] sign? digits)?
// suffix: n u m k M G T P E | Ki Mi Gi Ti Pi Ei       (api/quantity.py)
static Parsed parse(const char* s) {
    Parsed p = {false, false, 0, 0, 0, false};
    if (s == nullptr) return p;
    const char* c = s;
    while (isspace((unsigned char)*c)) c++;
    if (*c == '+') c++;
    else if (*c == '-') { p.negative = true; c++; }

    bool any_digit = false;
    bool saturated = false;
    int frac_digits = 0;
    bool in_frac = false;
    for (;; c++) {
        if (*c >= '0' && *c <= '9') {
            any_digit = true;
            if (!saturated) {
                // Overflow-safe: 10*m+d wraps mod 2^128 and can land back
                // above m, so a post-hoc `next < m` test misses wraps —
                // check against the ceiling before multiplying.
                const unsigned __int128 MAX_U128 = ~(unsigned __int128)0;
                unsigned d = (unsigned)(*c - '0');
                if (p.mantissa > (MAX_U128 - d) / 10) { saturated = true; p.inexact = true; }
                else p.mantissa = p.mantissa * 10 + d;
            }
            if (saturated && !in_frac) p.dec_exp++;  // keep magnitude
            if (in_frac && !saturated) frac_digits++;
        } else if (*c == '.') {
            if (in_frac) return p;  // two dots
            in_frac = true;
        } else {
            break;
        }
    }
    if (!any_digit) return p;
    p.dec_exp -= frac_digits;

    // Suffix / exponent.
    if (at_end(c)) { p.ok = true; return p; }
    if (*c == 'e' || *c == 'E') {
        // decimalExponent — but bare "E" (exa) has no digits after it.
        const char* d = c + 1;
        bool neg = false;
        if (*d == '+') d++;
        else if (*d == '-') { neg = true; d++; }
        if (*d >= '0' && *d <= '9') {
            int e = 0;
            for (; *d >= '0' && *d <= '9'; d++) {
                if (e < 1000) e = e * 10 + (*d - '0');
            }
            if (!at_end(d)) return p;
            p.dec_exp += neg ? -e : e;
            p.ok = true;
            return p;
        }
        if (*c == 'e') return p;  // lowercase 'e' with no digits: invalid
        // fall through: capital E is the exa suffix
    }

    char s0 = *c;
    char s1 = *(c + 1);
    if (s1 == 'i' && at_end(c + 2)) {
        switch (s0) {
            case 'K': p.bin_exp = 10; break;
            case 'M': p.bin_exp = 20; break;
            case 'G': p.bin_exp = 30; break;
            case 'T': p.bin_exp = 40; break;
            case 'P': p.bin_exp = 50; break;
            case 'E': p.bin_exp = 60; break;
            default: return p;
        }
        p.ok = true;
        return p;
    }
    if (!at_end(c + 1)) return p;
    switch (s0) {
        case 'n': p.dec_exp -= 9; break;
        case 'u': p.dec_exp -= 6; break;
        case 'm': p.dec_exp -= 3; break;
        case 'k': p.dec_exp += 3; break;
        case 'M': p.dec_exp += 6; break;
        case 'G': p.dec_exp += 9; break;
        case 'T': p.dec_exp += 12; break;
        case 'P': p.dec_exp += 15; break;
        case 'E': p.dec_exp += 18; break;
        default: return p;
    }
    p.ok = true;
    return p;
}

// ceil(value * scale) clamped to int64, where scale is 10^scale_exp10.
// cpu -> millicores: scale_exp10 = 3; memory -> bytes: scale_exp10 = 0.
static bool to_int_ceil(const Parsed& p, int scale_exp10, int64_t* out, bool* inexact) {
    if (!p.ok) return false;
    if (p.inexact && inexact) *inexact = true;
    int dec = p.dec_exp + scale_exp10;
    unsigned __int128 m = p.mantissa;
    if (m > (unsigned __int128)I128_MAX_SENTINEL) m = (unsigned __int128)I128_MAX_SENTINEL;
    __int128 num = (__int128)m;
    __int128 den = 1;
    if (dec >= 0) num = mul_sat(num, pow_sat(10, dec));
    else den = pow_sat(10, -dec);
    num = mul_sat(num, pow_sat(2, p.bin_exp > 0 ? p.bin_exp : 0));
    // Any rail hit in the scaling math means digits of precision were lost;
    // equality with the rail is conservatively treated as a hit (the caller
    // re-derives the exact value through the Python oracle).
    if (inexact && (num >= I128_MAX_SENTINEL || den >= I128_MAX_SENTINEL)) *inexact = true;

    __int128 q;
    if (p.negative) {
        // math.ceil of a negative value rounds toward zero: -floor(|x|).
        q = -(num / den);
    } else {
        q = (num + den - 1) / den;
    }
    if (q > (__int128)I64_MAX) q = I64_MAX;
    if (q < -(__int128)I64_MAX) q = -I64_MAX;
    *out = (int64_t)q;
    return true;
}

}  // namespace

extern "C" {

// Modes for batch_parse.
enum { MODE_CPU_MILLIS = 0, MODE_MEM_BYTES = 1 };

// Parse one quantity; returns 1 on success.
int tpusched_parse(const char* s, int mode, int64_t* out) {
    Parsed p = parse(s);
    if (!p.ok) return 0;
    return to_int_ceil(p, mode == MODE_CPU_MILLIS ? 3 : 0, out, nullptr) ? 1 : 0;
}

// Batch parse: returns -1 on full success, else the index of the first
// invalid quantity.  `strs` is an array of NUL-terminated UTF-8 strings.
// `inexact` (nullable, [n]) is set to 1 where saturation made the result
// approximate — the Python wrapper recomputes those via the exact oracle.
int64_t tpusched_batch_parse_ex(const char** strs, int64_t n, int mode, int64_t* out, unsigned char* inexact) {
    int scale = (mode == MODE_CPU_MILLIS) ? 3 : 0;
    for (int64_t i = 0; i < n; i++) {
        bool inx = false;
        Parsed p = parse(strs[i]);
        if (!p.ok || !to_int_ceil(p, scale, &out[i], &inx)) return i;
        if (inexact) inexact[i] = inx ? 1 : 0;
    }
    return -1;
}

int64_t tpusched_batch_parse(const char** strs, int64_t n, int mode, int64_t* out) {
    return tpusched_batch_parse_ex(strs, n, mode, out, nullptr);
}

// Batch pack of pod requests: given per-pod (cpu_str, mem_str) arrays,
// produce the int32 (millicores, KiB-ceil) rows of ops/pack.py, clamped to
// int32 — the tensor-packing fast path.  Returns -1 or first bad index.
int64_t tpusched_pack_requests_ex(const char** cpu_strs, const char** mem_strs, int64_t n, int32_t* out /* [n,2] */,
                                  unsigned char* inexact /* nullable, [n] */) {
    const int64_t I32_MAX = 2147483647LL;
    for (int64_t i = 0; i < n; i++) {
        int64_t cpu = 0, mem = 0;
        bool inx = false;
        if (cpu_strs[i] != nullptr) {
            Parsed p = parse(cpu_strs[i]);
            if (!p.ok || !to_int_ceil(p, 3, &cpu, &inx)) return i;
        }
        if (mem_strs[i] != nullptr) {
            Parsed p = parse(mem_strs[i]);
            if (!p.ok || !to_int_ceil(p, 0, &mem, &inx)) return i;
        }
        if (inexact) inexact[i] = inx ? 1 : 0;
        int64_t kib = (mem >= 0) ? (mem + 1023) / 1024 : mem / 1024;
        out[i * 2] = (int32_t)(cpu > I32_MAX ? I32_MAX : (cpu < -I32_MAX ? -I32_MAX : cpu));
        out[i * 2 + 1] = (int32_t)(kib > I32_MAX ? I32_MAX : (kib < -I32_MAX ? -I32_MAX : kib));
    }
    return -1;
}

int64_t tpusched_pack_requests(const char** cpu_strs, const char** mem_strs, int64_t n, int32_t* out /* [n,2] */) {
    return tpusched_pack_requests_ex(cpu_strs, mem_strs, n, out, nullptr);
}

}  // extern "C"
