"""jit-stability-smoke — the compile-cache boundedness standing gate (make check).

Two contracts, runnable standalone for a verdict (exit 0 = green), the
`make delta-smoke` pattern:

  1. STATIC — the JITC/XFER analyzer rules (scripts/analyze/jitc.py) must
     come back clean over the annotated tree: every padding dimension that
     reaches a ``jax.jit`` root provably round-up bucketed, every declared
     hot path free of undeclared host syncs.
  2. STEADY — the steady-state scenario driven by the REAL ``TpuBackend``
     (JAX on CPU — the pure-numpy NativeBackend would leave the compile
     listener uninstalled and the gate vacuous) must pass its scorecard
     with the ``compile`` block live (``enabled``) and FLAT: zero XLA
     compiles after the warmup window.  This is the runtime twin of
     contract 1 — a raw per-cycle dim the static pass missed shows up here
     as a post-warmup retrace.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import subprocess
import sys


def main() -> int:
    import logging

    # 1. static: the JITC/XFER rule subset over the whole tree, findings
    # fatal (baseline pins would surface as baselined counts; there are
    # none and this gate keeps it that way for these two families).
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.analyze", "--rule", "JITC,XFER"],
        capture_output=True,
        text=True,
    )
    print(proc.stdout.strip() or proc.stderr.strip())
    if proc.returncode != 0:
        print("FAIL: JITC/XFER static analysis found compile-stability hazards", file=sys.stderr)
        return 1

    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.sim.harness import run_scenario

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    # 2. steady: the scenario's pass gate REQUIRES the compile block ok,
    # but under NativeBackend that is vacuous — drive the TpuBackend so
    # ``enabled`` is true and the flatness assertion counts real XLA
    # compiles.
    card = run_scenario("steady-state", seed=0, backend=TpuBackend())
    comp = card["compile"]
    print(
        f"steady-state(TpuBackend): pass={card['pass']} enabled={comp['enabled']} "
        f"warmup_cycles={comp['warmup_cycles']} post_warmup_compiles={comp['post_warmup_compiles']}"
    )
    if not comp["enabled"]:
        print("FAIL: compile listener not installed — the flatness gate is vacuous", file=sys.stderr)
        return 1
    if not card["pass"] or not comp["ok"]:
        print("FAIL: steady-state scorecard (compile block) is red", file=sys.stderr)
        return 1
    if comp["post_warmup_compiles"] != 0:
        print(
            f"FAIL: {comp['post_warmup_compiles']} XLA compiles after the "
            f"{comp['warmup_cycles']}-cycle warmup window — a shape bucket is leaking",
            file=sys.stderr,
        )
        return 1
    print("jit-stability-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
