#!/usr/bin/env python
"""Jitter-amplitude sweep on the constrained flagship cycle.

Hypothesis (diag_constrained_tail): the 64-round tail is anti-affinity
HERDING — each app's ~200 mutually-repelling pods pick the same near-tied
best node, and the AA within-round filter admits one per (term, node) per
round.  If so, a larger tie-break amplitude should collapse rounds/time.

Usage: python scripts/diag_jitter_sweep.py [pods] [nodes]
"""
import os
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    base = PROFILES["throughput"].with_(pod_block=8192, max_rounds=64)
    snap = synth_cluster(
        n_nodes=nodes, n_pending=pods, n_bound=2 * nodes, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=base.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    backend = TpuBackend()
    for amp in (0.5, 2.0, 8.0, 32.0):
        prof = base.with_(spread_jitter=amp)
        r = backend.schedule(packed, prof)  # warm (weights are operands: no recompile)
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            r = backend.schedule(packed, prof)
            times.append(time.perf_counter() - t0)
        print(f"jitter={amp:5.1f}: {min(times):.3f}s bound={len(r.bindings)}/{packed.num_pods} rounds={r.rounds}", flush=True)


if __name__ == "__main__":
    main()
