"""elasticity-smoke — the closed-loop autoscaler's standing gate (make check).

Two contracts, runnable standalone for a verdict (exit 0 = green), the
`make defrag-smoke` / `make latency-smoke` pattern:

  1. ELASTIC — the ``flash-crowd-provisioning-lag`` scenario (seed 0)
     must pass its scorecard with the ``elasticity`` block green: the
     joint cost+SLO objective (effective p99 time-to-bind plus the
     weighted elastic-capacity cost integral) at or under the scenario
     gate, with zero reclaim-orphaned pods — and the autoscaler must
     have actually bought capacity.
  2. BASELINE — the SAME scenario with the autoscaler forced OFF
     (``run_scenario(..., autoscale=False)``) must FAIL the same joint
     gate on the static fleet: if the baseline ever passes, the gate
     stopped measuring elasticity and the scenario must be re-tuned.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import sys

SCENARIO = "flash-crowd-provisioning-lag"


def main() -> int:
    import logging

    from tpu_scheduler.sim.harness import run_scenario

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    card = run_scenario(SCENARIO, seed=0)
    e = card["elasticity"]
    print(
        f"elasticity-smoke ON: pass={card['pass']} joint={e['joint_objective']} "
        f"(gate {e['objective_gate']}) scale_ups={sum(e['scale_ups'].values())} "
        f"scale_downs={sum(e['scale_downs'].values())} lag_p99={e['provision_lag_p99_s']}s "
        f"cost={e['cost_node_hours']} node-h orphans={e['reclaim_orphans']}"
    )
    if not card["pass"] or not e["ok"]:
        print("FAIL: elasticity-smoke scorecard (elasticity block) is red", file=sys.stderr)
        return 1
    if sum(e["scale_ups"].values()) == 0:
        print("FAIL: the autoscaler bought no capacity — the gate proved nothing", file=sys.stderr)
        return 1

    off = run_scenario(SCENARIO, seed=0, autoscale=False)
    eo = off["elasticity"]
    print(
        f"elasticity-smoke OFF: pass={off['pass']} joint={eo['joint_objective']} "
        f"(gate {eo['objective_gate']})"
    )
    if off["pass"] or eo["ok"]:
        print(
            "FAIL: the autoscaler-off baseline passed the joint gate — the scenario no longer "
            "measures elasticity",
            file=sys.stderr,
        )
        return 1
    if eo["joint_objective"] <= e["joint_objective"]:
        print("FAIL: elastic capacity did not improve the joint objective over the static baseline", file=sys.stderr)
        return 1
    print("elasticity-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
