#!/usr/bin/env python
"""Probe the axon TPU backend with bounded retries; write status to scripts/tpu_status.json.

Never killed mid-compile (that wedges the tunnel) — each attempt lets jax.devices()
run to completion or raise on its own.
"""
import json
import os
import sys
import time

STATUS = os.path.join(os.path.dirname(__file__), "tpu_status.json")


def write(d):
    d["ts"] = time.time()  # bench.py consults freshness to size its retry budget
    with open(STATUS, "w") as f:
        json.dump(d, f)


def main():
    attempts = int(os.environ.get("TPU_PROBE_ATTEMPTS", "10"))
    start = int(os.environ.get("TPU_PROBE_ATTEMPT", "0"))
    for i in range(start, attempts):
        t0 = time.time()
        try:
            import jax

            devs = jax.devices()
            # Prove execution, not just enumeration.
            import jax.numpy as jnp

            x = jnp.ones((256, 256), dtype=jnp.bfloat16)
            (x @ x).block_until_ready()
            dt = time.time() - t0
            write(
                {
                    "ok": True,
                    "attempt": i,
                    "init_seconds": round(dt, 1),
                    "devices": [str(d) for d in devs],
                    "platform": devs[0].platform,
                }
            )
            print(f"TPU OK after {dt:.1f}s: {devs}", flush=True)
            return 0
        except Exception as e:  # noqa: BLE001
            dt = time.time() - t0
            msg = f"{type(e).__name__}: {e}"
            print(f"attempt {i}: failed after {dt:.1f}s: {msg[:300]}", flush=True)
            write({"ok": False, "attempt": i, "error": msg[:1000], "init_seconds": round(dt, 1)})
            # jax caches the failed backend; must re-exec to retry cleanly.
            if i + 1 < attempts:
                time.sleep(min(120, 15 * (i + 1)))
                os.environ["TPU_PROBE_ATTEMPT"] = str(i + 1)
                os.execv(sys.executable, [sys.executable, __file__])
    return 1


if __name__ == "__main__":
    sys.exit(main())
