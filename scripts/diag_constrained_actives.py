#!/usr/bin/env python
"""Per-round active/accept trajectory of the constrained flagship cycle.

Drives ONE auction round at a time (ops/assign._make_round_body jitted at
full size) and fetches n_active after each round — slow (64 host syncs) but
shows exactly which rounds keep how many pods active, i.e. whether the
eventual residue pins the size chain at large stages.

Usage: python scripts/diag_constrained_actives.py [pods] [nodes] [rounds]
"""
import os
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes_n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    max_rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops import assign as A
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192)
    snap = synth_cluster(
        n_nodes=nodes_n, n_pending=pods, n_bound=2 * nodes_n, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    arrays = {k: jax.device_put(v) for k, v in packed.device_arrays().items()}
    nodes, ps = A.split_device_arrays(arrays)
    ps.update({k: jax.device_put(v) for k, v in cons.pod_arrays().items()})
    cmeta = {k: jax.device_put(v) for k, v in cons.meta_arrays().items()}
    cstate = {k: jax.device_put(v) for k, v in cons.state_arrays().items()}
    cstate = {**cstate, "stall": jnp.int32(0)}
    weights = jax.device_put(profile.weights())

    soft_spread = cons.n_spread_soft > 0
    soft_pa = cons.n_ppa_terms > 0
    hard_pa = cons.n_pa_terms > 0

    import functools

    @functools.partial(jax.jit, static_argnames=("block",))
    def prelude(nodes, ps, block):
        perm, out = A._prepare_pods(ps, block)
        return perm, out, nodes["node_avail"]

    body_fn = A._make_round_body(nodes, weights, profile.pod_block, False, False, cmeta, soft_spread, soft_pa, hard_pa)

    @jax.jit
    def one_round(avail, ps, n_active, rounds, cst):
        return body_fn((avail, ps, n_active, rounds, cst))

    perm, ps, avail = prelude(nodes, ps, profile.pod_block)
    n_active = ps["active"].sum(dtype=jnp.int32)
    rounds = jnp.int32(0)
    prev_assigned = (ps["assigned"] >= 0).sum()
    print(f"start: active={int(n_active)}", flush=True)
    t_all = time.perf_counter()
    prev_active = int(n_active)
    for r in range(max_rounds):
        t0 = time.perf_counter()
        avail, ps, n_active, rounds, cstate = one_round(avail, ps, n_active, rounds, cstate)
        na = int(n_active)  # sync
        dt = time.perf_counter() - t0
        assigned_now = int((ps["assigned"] >= 0).sum())
        acc = assigned_now - int(prev_assigned)
        dropped = prev_active - na - acc
        prev_assigned = assigned_now
        prev_active = na
        print(
            f"round {r:3d}: active={na:6d} accepted={acc:6d} dropped={dropped:6d} stall={int(cstate['stall'])} {dt*1e3:7.1f}ms",
            flush=True,
        )
        if na == 0 or int(cstate["stall"]) >= 6:
            break
    print(f"total {time.perf_counter()-t_all:.1f}s (incl. sync overhead)", flush=True)


if __name__ == "__main__":
    main()
