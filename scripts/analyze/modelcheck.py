"""MODL — bounded explicit-state model checking of ``# protocol:`` specs.

PROT proves the code stays inside each machine's declared transition
relation; this pass proves the MACHINE ITSELF keeps its promises when the
world misbehaves.  Each spec's ``action``/``env`` lines compose the
protocol with its crash/retry/timeout environment — the actor dies
between any two steps, a message is delivered twice (an enabled action
can always re-fire) or never (the explorer also takes the path where it
doesn't), TTLs fire — and the explorer exhaustively enumerates every
reachable composite state ``(state, var values)``:

* ``invariant`` lines are safety properties: checked in every reachable
  state; a violation is reported with the minimal action trace that
  reaches it (BFS with deterministic, declaration-ordered successors).
* ``progress`` lines are no-stuck properties: any reachable state whose
  condition holds must have at least one enabled action — otherwise the
  protocol has wedged (e.g. an expired lease nobody can ever reclaim).

Vars saturate at their declared bounds, so the composite space is finite
by construction; a runaway spec trips MAX_STATES and reports that instead
of hanging the 5s analyze budget.  The pass is full-context (not
FILE_SCOPED, like EXCP): a spec edit anywhere re-verifies that machine
regardless of which files changed.

``LAST_STATS`` exposes per-machine exploration stats (states, transitions,
violations) after each run; the driver folds it into ``--json-out`` and
bench.py records it as provenance.
"""

from __future__ import annotations

from collections import deque

from .core import Context, Finding
from . import protocol
from .protocol import MachineSpec, eval_cond

CODES = {
    "MODL": "a # protocol: machine composed with its crash/retry environment reaches a state violating a declared invariant or progress property (minimal trace in the finding)",
}

FILE_SCOPED = False

# Composite-space cap per machine.  The committed specs sit around 10-40
# states each; 20k is a runaway-spec backstop, not a tuning knob.
MAX_STATES = 20_000

# Per-machine exploration stats from the most recent run(), keyed by
# machine name: {"file", "states", "transitions", "invariants",
# "progress", "violations"}.  The driver folds this into --json-out.
LAST_STATS: dict = {}


def _apply_effects(action, env: dict, bounds: dict) -> dict:
    out = dict(env)
    for var, op, val in action.effects:
        cur = out[var]
        nxt = val if op == "=" else (cur + val if op == "+=" else cur - val)
        lo, hi = bounds[var]
        out[var] = min(hi, max(lo, nxt))  # saturating
    return out


def _successors(spec: MachineSpec, state: str, env: dict, bounds: dict):
    """Enabled actions in declaration order — determinism gives every
    violation a stable, minimal trace."""
    for a in spec.actions:
        if a.frm != "*" and a.frm != state:
            continue
        if a.requires is not None and not eval_cond(a.requires, state, env):
            continue
        to = state if a.to == "*" else a.to
        yield a, to, _apply_effects(a, env, bounds)


def explore(spec: MachineSpec) -> dict:
    """Exhaustive BFS over the composite space.

    Returns {"states": int, "transitions": int, "violations":
    [(kind, name, trace, line)], "capped": bool} where trace is the
    minimal action-name sequence from the initial state.
    """
    bounds = {v.name: (v.lo, v.hi) for v in spec.vars}
    init = (spec.init, tuple(v.init for v in spec.vars))
    var_names = [v.name for v in spec.vars]

    def as_env(values: tuple) -> dict:
        return dict(zip(var_names, values))

    parent: dict = {init: None}  # composite -> (prev composite, action name)
    queue = deque([init])
    transitions = 0
    capped = False
    violations: list = []
    seen_violation: set = set()  # (kind, name) — first (minimal) trace only

    def trace_to(node) -> list:
        steps: list = []
        while parent[node] is not None:
            prev, aname = parent[node]
            steps.append(aname)
            node = prev
        steps.reverse()
        return steps

    def check(node) -> None:
        state, values = node
        env = as_env(values)
        for name, cond, line in spec.invariants:
            if ("invariant", name) not in seen_violation and not eval_cond(cond, state, env):
                seen_violation.add(("invariant", name))
                violations.append(("invariant", name, trace_to(node), line))
        if spec.progress:
            stuck = not any(True for _ in _successors(spec, state, env, bounds))
            if stuck:
                for name, cond, line in spec.progress:
                    if ("progress", name) not in seen_violation and eval_cond(cond, state, env):
                        seen_violation.add(("progress", name))
                        violations.append(("progress", name, trace_to(node), line))

    check(init)
    while queue:
        node = queue.popleft()
        state, values = node
        env = as_env(values)
        for action, to, nenv in _successors(spec, state, env, bounds):
            transitions += 1
            nxt = (to, tuple(nenv[n] for n in var_names))
            if nxt not in parent:
                if len(parent) >= MAX_STATES:
                    capped = True
                    queue.clear()
                    break
                parent[nxt] = (node, action.name)
                check(nxt)
                queue.append(nxt)

    return {
        "states": len(parent),
        "transitions": transitions,
        "violations": violations,
        "capped": capped,
    }


def _fmt_state(spec: MachineSpec, trace: list) -> str:
    return " -> ".join(trace) if trace else "(initial state)"


def run(ctx: Context) -> list:
    findings: list[Finding] = []
    LAST_STATS.clear()
    for f in ctx.parsed():
        # Parse errors are PROT's to report; here broken specs are absent.
        machines, _ = protocol.collect_machines(f)
        for spec, _cls in machines:
            result = explore(spec)
            LAST_STATS[spec.name] = {
                "file": spec.rel,
                "states": result["states"],
                "transitions": result["transitions"],
                "invariants": len(spec.invariants),
                "progress": len(spec.progress),
                "violations": len(result["violations"]),
            }
            if result["capped"]:
                findings.append(
                    Finding(
                        "MODL", spec.rel, spec.line,
                        f"machine '{spec.name}': composite state space exceeds {MAX_STATES} states — tighten var bounds",
                    )
                )
                continue
            for kind, name, trace, line in result["violations"]:
                what = "violated" if kind == "invariant" else "stuck (no enabled action)"
                findings.append(
                    Finding(
                        "MODL", spec.rel, line,
                        f"machine '{spec.name}': {kind} '{name}' {what} after: {_fmt_state(spec, trace)}",
                    )
                )
    return findings
