"""DEAD — a non-underscore symbol in a package module's ``__all__`` that no
other analyzed file references (the round-2 'three dead soft scorers'
regression class).  Cross-file by construction: runs over the whole
``Context``, not per module."""

from __future__ import annotations

import re
from collections import Counter

from .core import Context, Finding, module_all

CODES = {
    "DEAD": "an __all__ export referenced nowhere else in the repo — API rot the round-2 regression shipped",
}

# Cross-file by construction: a partial (--changed-only) context would call
# every export of a changed module dead just because its callers were not
# loaded — this pass only runs on full-context runs.
FILE_SCOPED = False

_WORD_RE = re.compile(r"\w+")


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    # One word-frequency index per file instead of one regex scan per
    # (export, file) pair — the O(exports × files) rescans used to dominate
    # the whole suite's wall clock (the --budget gate's worst offender).
    counts = {f.rel: Counter(_WORD_RE.findall(f.text)) for f in ctx.files}
    for f in ctx.parsed():
        if "tpu_scheduler" not in f.rel or f.path.name == "__init__.py":
            continue
        for name in module_all(f.tree):
            refs = 0
            for rel, words in counts.items():
                hits = words[name]
                if rel == f.rel:
                    # definition + __all__ entry account for 2 mentions
                    refs += max(0, hits - 2)
                else:
                    refs += hits
            if refs == 0:
                findings.append(Finding("DEAD", f.rel, 1, f"export '{name}' is referenced nowhere"))
    return findings
