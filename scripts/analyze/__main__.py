"""``python -m scripts.analyze`` — the analysis suite's entry point."""

import sys

from .driver import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
