"""JITC / XFER — compile-cache boundedness and host-sync discipline.

The TPU design rests on one claim (ops/pack.py): shapes are padded to
static buckets, so XLA recompiles only when a bucket grows.  Nothing
checked that claim statically — any raw per-cycle dim (a ``len(pending)``,
an un-rounded pad) leaking into a ``jax.jit`` signature turns the
sub-100 ms delta cycle into a retrace storm that no unit test notices
(results stay correct; only the compile cache explodes).  This pass makes
bucket discipline machine-checked, with a runtime twin in the scorecard
``compile`` block (sim/harness.py): statically proven bounded, dynamically
proven flat after warmup.

**JITC (compile-cache boundedness).**  A padding site declares its bucketed
dims in a ``# bucket:`` comment directly above the ``def`` (decorators may
sit between — the ``# shape:`` placement rule)::

    # bucket: n_pad p_pad
    def pack_snapshot(...):
        n_pad = round_up(n_real, node_block)

Two contract forms:

  ``# bucket: name1 name2 ...``  every binding of each named local must be
    a ROUND-UP IDIOM: a call to a bucket primitive (below), ``max``/``min``
    /arithmetic over already-bucketed values, an integer constant, a
    carried attribute (``packed.padded_pods``), a ``.shape[...]`` read
    (tensor dims are bucketed by induction), or a static jit parameter.
    A binding from anything else — a raw ``len()``, an unrounded parameter
    — is an unbounded-retrace finding.  A declared name that is never
    bound is contract rot (same finding class as SHPE's).

  ``# bucket: return``  the function IS a bucketing primitive — its body
    must contain a round-up idiom (next-multiple arithmetic
    ``((x + m - 1) // m) * m`` or a power-of-2 doubling loop
    ``while size < n: size *= 2``); its name then resolves as an idiom at
    every call site (same-module first, then from-imports, the JAXP
    name-resolution pattern).

On top of the contracts, each ``jax.jit`` ROOT (decorator forms plus the
``jax.jit(f)`` call form, ``static_argnames`` parsed from the decorator)
is checked for the three classic cache-key leaks JAXP cannot see:

  • a non-static parameter driving Python control flow (``if``/``while``
    on its value, ``range(param)``) inside the jit body — per-call values
    retrace (or crash at trace when passed as an array);
  • a Python int/float literal passed traced at one call site of a root
    whose same parameter receives a non-literal elsewhere — the weak-typed
    literal promotes differently and retraces on the dtype flip;
  • ``jnp.array``/``jnp.asarray``/``device_put`` of a non-constant Python
    list inside a function that calls a jit root — a per-cycle host list
    is re-uploaded (and re-keyed) every call.

**XFER (host-sync discipline).**  JAXP forbids syncs INSIDE jit-reached
code; XFER governs the host side.  A per-cycle driver declares itself with
``# hotpath: <label>`` above its def; within it, every device→host
materialization — ``.item()``, ``float()``/``int()``/``bool()`` on a
device value, ``np.asarray``/``np.array`` of a device value,
``.block_until_ready()``, ``jax.device_get`` — must sit inside a declared
host-sync span: a ``with span("host-sync")`` block (the profiler's
attribution point) or a line carrying a trailing ``# host-sync: <reason>``
comment.  Device taint is light and local: results of calls to known jit
roots (or local aliases of them) and ``jnp.``/``lax.`` calls; ``int()``/
``float()``/``device_get``/``np.asarray`` drop taint (their result lives
on the host — they ARE the sync, flagged at the point).

Authoring guide: README "Static analysis" → "Bucket & hotpath contracts".
"""

from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

CODES = {
    "JITC": "a raw per-cycle dim, non-static scalar branch, or per-call host list reaching a jax.jit signature — unbounded retrace",
    "XFER": "a device->host sync inside a # hotpath: cycle driver outside a declared host-sync span — hidden per-cycle round-trip",
}

# Contracts are per-file; cross-module resolution (bucket primitives, jit
# root names) trusts what it cannot load — a partial (--changed-only)
# context yields fewer findings, never false ones.
FILE_SCOPED = True

# Per-run stats for the bench provenance row (the modelcheck.LAST_STATS
# pattern): how much of the tree the contracts actually cover.
LAST_STATS: dict[str, int] = {}

_SYNC_SPAN_TOKEN = "host-sync"
_SHAPE_ATTRS = ("shape",)


def _contract_above(f: SourceFile, node: ast.FunctionDef, tag: str) -> tuple[int, str] | None:
    """(lineno, payload) of the ``# <tag>: ...`` comment line directly above
    the def/decorator block, or None (the # shape: placement rule)."""
    start = min([node.lineno] + [d.lineno for d in node.decorator_list])
    i = start - 2  # 0-indexed line above the def/decorator block
    prefix = f"# {tag}:"
    while i >= 0 and f.lines[i].strip().startswith("#"):
        text = f.lines[i].strip()
        if text.startswith(prefix):
            return i + 1, text[len(prefix):].strip()
        i -= 1
    return None


def _is_jax_jit_expr(node: ast.expr) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _static_argnames(call: ast.Call) -> set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(kw.value, (ast.Tuple, ast.List)):
            return {e.value for e in kw.value.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)}
        if kw.arg == "static_argnames" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
            return {kw.value.value}
    return set()


def _jit_root_info(fn: ast.FunctionDef) -> set[str] | None:
    """static_argnames when ``fn`` is jit-decorated, else None."""
    for dec in fn.decorator_list:
        if _is_jax_jit_expr(dec):
            return set()
        if isinstance(dec, ast.Call):
            if _is_jax_jit_expr(dec.func):
                return _static_argnames(dec)
            fname = dec.func.attr if isinstance(dec.func, ast.Attribute) else getattr(dec.func, "id", None)
            if fname == "partial" and dec.args and _is_jax_jit_expr(dec.args[0]):
                return _static_argnames(dec)
    return None


class _ModIndex:
    """Per-module maps: function defs, imports, contracts."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.functions: dict[str, list[ast.FunctionDef]] = {}
        self.from_imports: set[str] = set()
        self.np_aliases: set[str] = set()
        self.taint_bases: set[str] = set()  # jnp / lax style namespaces
        self.jax_aliases: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_aliases.add(bound)
                    elif a.name == "jax":
                        self.jax_aliases.add(bound)
                    elif a.name == "jax.numpy" and a.asname:
                        self.taint_bases.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.from_imports.add(a.asname or a.name)
                    if node.module == "jax" and a.name in ("numpy", "lax"):
                        self.taint_bases.add(a.asname or a.name)

    def nested_defs(self, fn: ast.AST):
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


# -- bucket idiom verification -----------------------------------------------


def _is_shape_read(node: ast.expr) -> bool:
    """``x.shape[0]`` / ``a["k"].shape[...]`` / ``mesh.shape["dp"]`` — an
    existing tensor/mesh dim, bucketed by induction."""
    if isinstance(node, ast.Subscript):
        v = node.value
        return isinstance(v, ast.Attribute) and v.attr in _SHAPE_ATTRS
    return isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS


def _has_roundup_body(fn: ast.FunctionDef) -> bool:
    """A ``# bucket: return`` primitive must actually round: next-multiple
    arithmetic anywhere, or a power-of-2 doubling loop."""
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            if any(isinstance(s, ast.BinOp) and isinstance(s.op, ast.FloorDiv) for s in (node.left, node.right)):
                return True
        if isinstance(node, ast.While):
            for stmt in ast.walk(node):
                if (
                    isinstance(stmt, ast.AugAssign)
                    and isinstance(stmt.op, ast.Mult)
                    and isinstance(stmt.value, ast.Constant)
                    and stmt.value.value == 2
                ):
                    return True
    return False


class _BucketScope:
    """Decides whether an expression yields a bucketed (bounded-vocabulary)
    dim inside one contract-carrying function."""

    def __init__(self, declared: set[str], static_params: set[str], idx: _ModIndex, primitives: set[str]):
        self.declared = declared
        self.static_params = static_params
        self.idx = idx
        self.primitives = primitives
        self.derived: set[str] = set()

    def name_ok(self, name: str) -> bool:
        return name in self.declared or name in self.derived or name in self.static_params

    def expr_ok(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (int, bool)) or node.value is None
        if isinstance(node, ast.Name):
            return self.name_ok(node.id)
        if _is_shape_read(node):
            return True
        if isinstance(node, ast.Attribute):
            return True  # carried pad (packed.padded_pods) — padded upstream
        if isinstance(node, ast.Call):
            f = node.func
            fname = f.id if isinstance(f, ast.Name) else (f.attr if isinstance(f, ast.Attribute) else None)
            if fname in ("max", "min"):
                return bool(node.args) and all(self.expr_ok(a) for a in node.args)
            if isinstance(f, ast.Name):
                if f.id in self.primitives:
                    return True  # round-up primitive: raw in, bucketed out
                if f.id in self.idx.from_imports and f.id not in self.idx.functions:
                    return True  # unresolved import — trust, never false-flag
                return False
            if isinstance(f, ast.Attribute) and f.attr in self.primitives:
                return True
            return False
        if isinstance(node, ast.BinOp):
            return self.expr_ok(node.left) and self.expr_ok(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr_ok(node.operand)
        if isinstance(node, ast.IfExp):
            return self.expr_ok(node.body) and self.expr_ok(node.orelse)
        if isinstance(node, ast.Tuple):
            return all(self.expr_ok(e) for e in node.elts)
        return False


def _check_bucket_fn(
    f: SourceFile,
    fn: ast.FunctionDef,
    names: list[str],
    idx: _ModIndex,
    primitives: set[str],
    findings: list[Finding],
) -> None:
    static = _jit_root_info(fn) or set()
    scope = _BucketScope(set(names), static, idx, primitives)
    nested = set(idx.nested_defs(fn))
    bound: set[str] = set()

    def own_nodes(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if child in nested or isinstance(child, ast.Lambda):
                continue
            yield child
            yield from own_nodes(child)

    def check_binding(target: ast.expr, value: ast.expr) -> None:
        if isinstance(target, ast.Tuple) and isinstance(value, ast.Tuple) and len(target.elts) == len(value.elts):
            for t, v in zip(target.elts, value.elts):
                check_binding(t, v)
            return
        if not isinstance(target, ast.Name):
            return
        name = target.id
        ok = scope.expr_ok(value)
        if name in scope.declared:
            bound.add(name)
            if not ok:
                findings.append(
                    Finding(
                        "JITC",
                        f.rel,
                        value.lineno,
                        f"bucketed dim '{name}' in '{fn.name}' bound from a raw per-cycle value — "
                        "not a round-up idiom (# bucket: contract)",
                    )
                )
        elif ok:
            scope.derived.add(name)

    for node in own_nodes(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                check_binding(t, node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            check_binding(node.target, node.value)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            if node.target.id in scope.declared:
                bound.add(node.target.id)
                if not scope.expr_ok(node.value):
                    findings.append(
                        Finding(
                            "JITC",
                            f.rel,
                            node.lineno,
                            f"bucketed dim '{node.target.id}' in '{fn.name}' bound from a raw per-cycle value — "
                            "not a round-up idiom (# bucket: contract)",
                        )
                    )

    for name in sorted(scope.declared - bound):
        params = {a.arg for a in list(fn.args.args) + list(fn.args.kwonlyargs)}
        findings.append(
            Finding(
                "JITC",
                f.rel,
                fn.lineno,
                f"# bucket: contract rot — '{name}' is never bound in '{fn.name}'"
                + (" (it is a parameter; declare buckets where they are computed)" if name in params else ""),
            )
        )


# -- jit-root static discipline ----------------------------------------------


def _branch_value_names(test: ast.expr) -> set[str]:
    """Bare names whose VALUE the test consumes: the whole test, operands of
    not/and/or, and operands of non-``is`` comparisons.  Names inside
    subscripts/attributes/calls are structural, not per-call scalars."""
    out: set[str] = set()
    if isinstance(test, ast.Name):
        out.add(test.id)
    elif isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        out |= _branch_value_names(test.operand)
    elif isinstance(test, ast.BoolOp):
        for v in test.values:
            out |= _branch_value_names(v)
    elif isinstance(test, ast.Compare):
        if not all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            for operand in [test.left, *test.comparators]:
                if isinstance(operand, ast.Name):
                    out.add(operand.id)
    return out


def _check_jit_root(f: SourceFile, fn: ast.FunctionDef, static: set[str], findings: list[Finding]) -> None:
    params = {a.arg for a in list(fn.args.args) + list(fn.args.posonlyargs) + list(fn.args.kwonlyargs)} - {"self"}
    nonstatic = params - static
    # None-defaulted params are pytree/sentinel operands: ``if x is not
    # None`` is already excluded, and their truthiness never reaches a
    # Python branch in working code — skip them to avoid sentinel noise.
    defaults = list(fn.args.defaults)
    pos = list(fn.args.args)
    for arg, d in zip(pos[len(pos) - len(defaults):], defaults):
        if isinstance(d, ast.Constant) and d.value is None:
            nonstatic.discard(arg.arg)
    for arg, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
        if isinstance(d, ast.Constant) and d.value is None:
            nonstatic.discard(arg.arg)
    if not nonstatic:
        return
    for node in ast.walk(fn):
        if isinstance(node, (ast.If, ast.While)):
            for name in sorted(_branch_value_names(node.test) & nonstatic):
                findings.append(
                    Finding(
                        "JITC",
                        f.rel,
                        node.lineno,
                        f"Python branch on per-call scalar '{name}' in jit root '{fn.name}' — "
                        "add it to static_argnames (each value retraces; an array crashes at trace)",
                    )
                )
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "range":
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in nonstatic:
                    findings.append(
                        Finding(
                            "JITC",
                            f.rel,
                            node.lineno,
                            f"range() over per-call scalar '{a.id}' in jit root '{fn.name}' — "
                            "add it to static_argnames (the unrolled length keys the compile cache)",
                        )
                    )


# -- jit-root call sites: literal promotion + per-cycle host lists ------------


def _map_call_args(call: ast.Call, fn: ast.FunctionDef) -> dict[str, ast.expr]:
    names = [a.arg for a in fn.args.args]
    out: dict[str, ast.expr] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(names):
            out[names[i]] = a
    for kw in call.keywords:
        if kw.arg:
            out[kw.arg] = kw.value
    return out


def _nonconst_list(node: ast.expr) -> bool:
    if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
        return True
    if isinstance(node, ast.List):
        return any(not isinstance(e, ast.Constant) for e in node.elts)
    return False


# -- XFER: hotpath host-sync discipline ---------------------------------------


def _sync_span_ranges(fn: ast.FunctionDef) -> list[tuple[int, int]]:
    """Line ranges of ``with span("...host-sync...")`` blocks — declared
    host-sync spans (the profiler's attribution point)."""
    out: list[tuple[int, int]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            e = item.context_expr
            if not (isinstance(e, ast.Call) and e.args and isinstance(e.args[0], ast.Constant)):
                continue
            fname = e.func.id if isinstance(e.func, ast.Name) else (e.func.attr if isinstance(e.func, ast.Attribute) else None)
            if fname == "span" and isinstance(e.args[0].value, str) and _SYNC_SPAN_TOKEN in e.args[0].value:
                out.append((node.lineno, node.end_lineno or node.lineno))
    return out


def _check_hotpath(
    f: SourceFile,
    fn: ast.FunctionDef,
    label: str,
    idx: _ModIndex,
    root_names: set[str],
    stats: dict[str, int],
    findings: list[Finding],
) -> None:
    spans = _sync_span_ranges(fn)
    nested = set(idx.nested_defs(fn))
    tainted: set[str] = set()
    aliases = set(root_names)

    def allowed(lineno: int) -> bool:
        if any(lo <= lineno <= hi for lo, hi in spans):
            stats["allowed_syncs"] += 1
            return True
        if 0 < lineno <= len(f.lines) and "# host-sync:" in f.lines[lineno - 1]:
            stats["allowed_syncs"] += 1
            return True
        return False

    def is_device(node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Call):
            g = node.func
            if isinstance(g, ast.Name):
                if g.id in aliases:
                    return True
                if g.id in ("int", "float", "bool"):
                    return False  # the sync itself — result is host
                return any(is_device(a) for a in node.args)
            if isinstance(g, ast.Attribute):
                base = g.value
                if isinstance(base, ast.Name) and base.id in idx.taint_bases:
                    return True
                if isinstance(base, ast.Name) and base.id in idx.np_aliases:
                    return False  # numpy result lives on the host
                if isinstance(base, ast.Name) and base.id in idx.jax_aliases and g.attr == "device_get":
                    return False
                return is_device(base)
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "dtype", "ndim", "size"):
                return False
            return is_device(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return is_device(node.value)
        if isinstance(node, ast.BinOp):
            return is_device(node.left) or is_device(node.right)
        if isinstance(node, ast.UnaryOp):
            return is_device(node.operand)
        if isinstance(node, ast.Compare):
            return is_device(node.left) or any(is_device(c) for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(is_device(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return is_device(node.body) or is_device(node.orelse)
        return False

    def flag(lineno: int, what: str) -> None:
        if not allowed(lineno):
            findings.append(
                Finding(
                    "XFER",
                    f.rel,
                    lineno,
                    f"{what} in # hotpath: '{fn.name}' ({label}) outside a declared host-sync span — "
                    "wrap in `with span(\"host-sync\")` or justify with a trailing `# host-sync: <reason>`",
                )
            )

    def own_nodes(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if child in nested or isinstance(child, ast.Lambda):
                continue
            yield child
            yield from own_nodes(child)

    for node in [fn, *own_nodes(fn)]:
        if isinstance(node, ast.Assign):
            if isinstance(node.value, ast.Name) and node.value.id in aliases:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
            elif isinstance(node.value, ast.IfExp) and all(
                isinstance(b, ast.Name) and b.id in aliases for b in (node.value.body, node.value.orelse)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
            elif is_device(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            if is_device(node.value) or node.target.id in tainted:
                tainted.add(node.target.id)
        elif isinstance(node, ast.For):
            if is_device(node.iter):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                if is_device(gen.iter):
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        if isinstance(node, ast.Call):
            g = node.func
            if isinstance(g, ast.Attribute):
                if g.attr == "item" and not node.args and is_device(g.value):
                    flag(node.lineno, ".item() device fetch")
                elif g.attr == "block_until_ready":
                    flag(node.lineno, ".block_until_ready() device barrier")
                elif (
                    isinstance(g.value, ast.Name)
                    and g.value.id in idx.np_aliases
                    and g.attr in ("asarray", "array")
                    and node.args
                    and is_device(node.args[0])
                ):
                    flag(node.lineno, f"np.{g.attr}() materialization of a device value")
                elif g.attr == "device_get":
                    flag(node.lineno, "jax.device_get() device fetch")
            elif isinstance(g, ast.Name):
                if g.id in ("float", "int", "bool") and node.args and is_device(node.args[0]):
                    flag(node.lineno, f"{g.id}() on a device value (blocking fetch)")
                elif g.id == "device_get":
                    flag(node.lineno, "device_get() device fetch")


# -- driver -------------------------------------------------------------------


def run(ctx: Context) -> list[Finding]:
    stats = {
        "bucket_contracts": 0,
        "bucket_dims": 0,
        "bucket_primitives": 0,
        "hotpath_contracts": 0,
        "jit_roots": 0,
        "root_call_sites": 0,
        "allowed_syncs": 0,
    }
    LAST_STATS.clear()
    findings: list[Finding] = []
    files = [f for f in ctx.parsed() if f.in_package("tpu_scheduler")]
    # Index construction walks the whole module AST, so it is LAZY: most
    # files carry no contracts, no jit decorators, and no root call sites,
    # and a cheap substring test proves it without a walk.
    indices: dict[str, _ModIndex] = {}

    def idx_of(f: SourceFile) -> _ModIndex:
        got = indices.get(f.rel)
        if got is None:
            got = indices[f.rel] = _ModIndex(f)
        return got

    # Pass 1 — global sets: bucket primitives, jit roots (+ static names).
    primitives: set[str] = set()
    primitive_defs: list[tuple[SourceFile, ast.FunctionDef]] = []
    roots: list[tuple[SourceFile, ast.FunctionDef, set[str]]] = []
    bucket_fns: list[tuple[SourceFile, ast.FunctionDef, list[str]]] = []
    hot_fns: list[tuple[SourceFile, ast.FunctionDef, str]] = []
    for f in files:
        if "# bucket:" not in f.text and "# hotpath:" not in f.text and "jit" not in f.text:
            continue
        idx = idx_of(f)
        for defs in idx.functions.values():
            for fn in defs:
                static = _jit_root_info(fn)
                if static is not None:
                    roots.append((f, fn, static))
                c = _contract_above(f, fn, "bucket")
                if c is not None:
                    _lineno, payload = c
                    names = payload.split()
                    if names == ["return"]:
                        primitives.add(fn.name)
                        primitive_defs.append((f, fn))
                    elif names:
                        bucket_fns.append((f, fn, names))
                        stats["bucket_dims"] += len(names)
                    stats["bucket_contracts"] += 1
                h = _contract_above(f, fn, "hotpath")
                if h is not None:
                    hot_fns.append((f, fn, h[1] or fn.name))
                    stats["hotpath_contracts"] += 1
    stats["bucket_primitives"] = len(primitives)
    stats["jit_roots"] = len(roots)
    root_names = {fn.name for _f, fn, _s in roots}
    root_def = {fn.name: (fn, static) for _f, fn, static in roots}

    # Pass 2 — verify primitives actually round.
    for f, fn in primitive_defs:
        if not _has_roundup_body(fn):
            findings.append(
                Finding(
                    "JITC",
                    f.rel,
                    fn.lineno,
                    f"# bucket: return on '{fn.name}' but its body has no round-up idiom "
                    "(next-multiple arithmetic or power-of-2 doubling loop)",
                )
            )

    # Pass 3 — bucket contracts + jit-root static discipline.
    for f, fn, names in bucket_fns:
        _check_bucket_fn(f, fn, names, indices[f.rel], primitives, findings)
    for f, fn, static in roots:
        _check_jit_root(f, fn, static, findings)

    # Pass 4 — root call sites: weak-typed literal promotion (a param that
    # sees BOTH a bare literal and a non-literal across the tree promotes
    # differently per site and retraces on the flip) + per-cycle host lists.
    site_kinds: dict[tuple[str, str], set[str]] = {}
    literal_sites: dict[tuple[str, str], list[tuple[SourceFile, int, str]]] = {}
    for f in files:
        if not any(rn in f.text for rn in root_names):
            continue  # no textual mention of a root — no call sites to map
        idx = idx_of(f)
        for fname, defs in idx.functions.items():
            for caller in defs:
                calls_root = False
                for node in ast.walk(caller):
                    if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
                        continue
                    cname = node.func.id
                    if cname not in root_names:
                        continue
                    if cname not in idx.functions and cname not in idx.from_imports:
                        continue  # unrelated same-name symbol
                    calls_root = True
                    stats["root_call_sites"] += 1
                    fn, static = root_def[cname]
                    for pname, arg in _map_call_args(node, fn).items():
                        if pname in static:
                            continue
                        key = (cname, pname)
                        if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)) and not isinstance(arg.value, bool):
                            kind = "literal"
                            literal_sites.setdefault(key, []).append((f, node.lineno, type(arg.value).__name__))
                        else:
                            kind = "value"
                        site_kinds.setdefault(key, set()).add(kind)
                if calls_root:
                    for node in ast.walk(caller):
                        if not isinstance(node, ast.Call):
                            continue
                        g = node.func
                        gname = g.attr if isinstance(g, ast.Attribute) else (g.id if isinstance(g, ast.Name) else None)
                        base_ok = not isinstance(g, ast.Attribute) or (
                            isinstance(g.value, ast.Name)
                            and g.value.id in (idx.taint_bases | idx.jax_aliases)
                        )
                        if gname in ("array", "asarray", "device_put") and base_ok and node.args and _nonconst_list(node.args[0]):
                            if isinstance(g, ast.Name) and gname in ("array", "asarray"):
                                continue  # bare array()/asarray() is not jnp's
                            findings.append(
                                Finding(
                                    "JITC",
                                    f.rel,
                                    node.lineno,
                                    f"{gname}() of a per-cycle Python list in '{caller.name}' (a jit call path) — "
                                    "build it once or pack it as a bucketed tensor",
                                )
                            )
    for key, kinds in site_kinds.items():
        if kinds == {"literal", "value"}:
            cname, pname = key
            for f, lineno, typename in literal_sites[key]:
                findings.append(
                    Finding(
                        "JITC",
                        f.rel,
                        lineno,
                        f"weak-typed {typename} literal passed traced for '{pname}' of jit root '{cname}' — "
                        "other sites pass a value; the promotion flip retraces (wrap in jnp.asarray or make it static)",
                    )
                )

    # Pass 5 — XFER hotpath discipline.
    for f, fn, label in hot_fns:
        _check_hotpath(f, fn, label, indices[f.rel], root_names, stats, findings)

    LAST_STATS.update(stats)
    return findings
