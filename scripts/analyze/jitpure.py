"""JAXP — jit purity: no host syncs inside the jitted hot paths.

Every function reached from a ``jax.jit`` root (decorator forms
``@jax.jit`` / ``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit,
...)``, or a ``jax.jit(f)`` call on a named function) is traced code: a
host sync there either crashes under trace or — worse — silently forces a
device round-trip per call.  Inside reached functions this pass forbids:

  • ``.item()``                      — the canonical device->host sync
  • ``float()``/``int()``/``bool()`` on a TRACED expression (see taint)
  • ``np.asarray`` / ``np.array``    — numpy materialization of a tracer
  • ``print``                        — host I/O under trace fires per call
  • ``time.*``                       — wall clock has no meaning in a trace
  • ``if``/``while`` on a TRACED expression — Python control flow cannot
    branch on a tracer (use ``lax.cond``/``jnp.where``)

Reachability is a name-resolved transitive closure: bare-name calls and
bare-name references (functions handed to ``lax.while_loop`` etc.) resolve
to same-module functions first, then to from-imported functions defined in
any analyzed module; nested defs of a reached function are reached.

Taint is a per-function forward pass: values returned by ``jnp.*`` /
``lax.*`` calls are traced; arithmetic/comparison/subscript over traced
values stays traced; ``.shape``/``.dtype``/``.ndim`` drop taint (static
under trace).  Function parameters are deliberately NOT tainted — jitted
helpers thread static config (block sizes, flags) through arguments, and
flagging every ``if use_pallas:`` would bury the real findings.
"""

from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

CODES = {
    "JAXP": "host sync or Python branch on a tracer inside jit-reached code — crashes or hides a device round-trip",
}

# Reachability roots resolve within the loaded context; an unloaded caller
# just means an unreached (unchecked) function — fewer findings under a
# partial (--changed-only) context, never false ones.
FILE_SCOPED = True

_STATIC_ATTRS = ("shape", "dtype", "ndim", "aval", "size")


class _ModuleIndex:
    """Per-module maps the reachability closure needs."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.functions: dict[str, list[ast.FunctionDef]] = {}  # name -> defs (any nesting)
        self.parents: dict[ast.AST, ast.AST] = {}
        self.from_imports: set[str] = set()  # names bound by from-imports
        self.np_aliases: set[str] = set()
        self.time_aliases: set[str] = set()
        self.taint_bases: set[str] = set()  # jnp/lax-style aliases
        tree = sf.tree
        assert tree is not None
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_aliases.add(bound)
                    elif a.name == "time":
                        self.time_aliases.add(bound)
                    elif a.name == "jax.numpy" and a.asname:
                        self.taint_bases.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    self.from_imports.add(bound)
                    if node.module == "jax" and a.name in ("numpy", "lax"):
                        self.taint_bases.add(bound)
                    elif node.module == "time":
                        self.time_aliases.add(bound)

    def nested_defs(self, fn: ast.AST):
        for node in ast.walk(fn):
            if node is not fn and isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node


def _is_jax_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` or bare ``jit``."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_decorated(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if _is_jax_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jax_jit_expr(dec.func):
                return True
            # functools.partial(jax.jit, ...) / partial(jax.jit, ...)
            fname = dec.func.attr if isinstance(dec.func, ast.Attribute) else getattr(dec.func, "id", None)
            if fname == "partial" and dec.args and _is_jax_jit_expr(dec.args[0]):
                return True
    return False


def _collect_roots(idx: _ModuleIndex) -> tuple[set[ast.FunctionDef], set[str]]:
    """(locally-defined jit roots, root NAMES needing cross-module
    resolution) for one module."""
    roots: set[ast.FunctionDef] = set()
    foreign: set[str] = set()
    for defs in idx.functions.values():
        for fn in defs:
            if _jit_decorated(fn):
                roots.add(fn)
    # jax.jit(f) / jax.jit(builder(...)) — mark the named function (or the
    # builder whose nested defs are the real jitted body).
    for node in ast.walk(idx.sf.tree):
        if isinstance(node, ast.Call) and _is_jax_jit_expr(node.func) and node.args:
            target = node.args[0]
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Call):
                tn = target.func
                name = tn.id if isinstance(tn, ast.Name) else (tn.attr if isinstance(tn, ast.Attribute) else None)
            if name is None:
                continue
            if name in idx.functions:
                roots.update(idx.functions[name])
            elif name in idx.from_imports:
                foreign.add(name)
    return roots, foreign


def _reachable(indices: list[_ModuleIndex]) -> dict[ast.FunctionDef, _ModuleIndex]:
    by_name: dict[str, list[tuple[_ModuleIndex, ast.FunctionDef]]] = {}
    for idx in indices:
        for name, defs in idx.functions.items():
            for fn in defs:
                by_name.setdefault(name, []).append((idx, fn))
    reached: dict[ast.FunctionDef, _ModuleIndex] = {}
    work: list[tuple[_ModuleIndex, ast.FunctionDef]] = []
    for idx in indices:
        local, foreign = _collect_roots(idx)
        for fn in local:
            work.append((idx, fn))
        for name in foreign:
            work.extend(by_name.get(name, ()))
    while work:
        idx, fn = work.pop()
        if fn in reached:
            continue
        reached[fn] = idx
        for nested in idx.nested_defs(fn):
            work.append((idx, nested))
        # Bare-name references inside the body: same-module functions, else
        # from-imported functions defined in any analyzed module.
        local_names = set(idx.functions)
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                name = node.id
                if name in local_names:
                    for g in idx.functions[name]:
                        work.append((idx, g))
                elif name in idx.from_imports:
                    for other_idx, g in by_name.get(name, ()):
                        work.append((other_idx, g))
    return reached


def _taint_check(fn: ast.FunctionDef, idx: _ModuleIndex, findings: list[Finding]) -> None:
    rel = idx.sf.rel
    tainted: set[str] = set()
    nested = set(idx.nested_defs(fn))

    def is_tainted(node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                if isinstance(base, ast.Name) and base.id in idx.taint_bases:
                    return True
                if isinstance(base, ast.Attribute) and isinstance(base.value, ast.Name) and base.value.id in idx.taint_bases:
                    return True  # lax.linalg.x / jnp.linalg.x style
                return is_tainted(base)  # method call on a traced value
            return any(is_tainted(a) for a in node.args)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False  # static under trace
            return is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return is_tainted(node.left) or is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return is_tainted(node.left) or any(is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return is_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return is_tainted(node.body) or is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(is_tainted(e) for e in node.elts)
        return False

    def walk_own(node: ast.AST):
        """This function's own statements — nested defs are visited as their
        own reached functions, with their own taint scope."""
        for child in ast.iter_child_nodes(node):
            if child in nested or isinstance(child, ast.Lambda):
                continue
            yield child
            yield from walk_own(child)

    for node in [fn, *walk_own(fn)]:
        if isinstance(node, ast.Assign):
            if is_tainted(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Name):
            if is_tainted(node.value) or node.target.id in tainted:
                tainted.add(node.target.id)
        elif isinstance(node, (ast.If, ast.While)):
            if is_tainted(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                findings.append(
                    Finding(
                        "JAXP",
                        rel,
                        node.lineno,
                        f"Python '{kind}' on a traced expression in jit-reached '{fn.name}' (use lax.cond/jnp.where)",
                    )
                )
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr == "item":
                    findings.append(
                        Finding("JAXP", rel, node.lineno, f".item() host sync in jit-reached '{fn.name}'")
                    )
                elif (
                    isinstance(f.value, ast.Name)
                    and f.value.id in idx.np_aliases
                    and f.attr in ("asarray", "array")
                ):
                    findings.append(
                        Finding(
                            "JAXP", rel, node.lineno, f"np.{f.attr}() materializes a tracer in jit-reached '{fn.name}'"
                        )
                    )
                elif isinstance(f.value, ast.Name) and f.value.id in idx.time_aliases:
                    findings.append(
                        Finding(
                            "JAXP", rel, node.lineno, f"time.{f.attr}() wall-clock call in jit-reached '{fn.name}'"
                        )
                    )
            elif isinstance(f, ast.Name):
                if f.id == "print":
                    findings.append(
                        Finding("JAXP", rel, node.lineno, f"print() host I/O in jit-reached '{fn.name}'")
                    )
                elif f.id in ("float", "int", "bool") and node.args and is_tainted(node.args[0]):
                    findings.append(
                        Finding(
                            "JAXP",
                            rel,
                            node.lineno,
                            f"{f.id}() on a traced expression in jit-reached '{fn.name}' (host sync)",
                        )
                    )


def run(ctx: Context) -> list[Finding]:
    indices = [
        _ModuleIndex(f)
        for f in ctx.parsed()
        if f.in_package("tpu_scheduler") and ("jit" in f.text or "pallas" in f.text)
    ]
    findings: list[Finding] = []
    for fn, idx in sorted(_reachable(indices).items(), key=lambda kv: (kv[1].sf.rel, kv[0].lineno)):
        _taint_check(fn, idx, findings)
    return findings
