"""PROT — protocol state-machine contracts over the distributed control
plane, statically checked against the code.

The control plane is a set of small protocols — the circuit breaker
(closed→open→half-open), shard/replica leases, two-phase gang
reservations, the rebalancer's unbind→cordon→re-place drain, the provider
node lifecycle, the delta engine's commit-exactly-once ledger — each with
a closed state vocabulary and crash-safety claims that used to live only
in docstrings and sampled sim scenarios.  A ``# protocol:`` contract in
the comment block directly above the owning class makes the state machine
machine-readable; this pass proves the CODE stays inside it, and
``modelcheck.py`` (the MODL rule) proves the MACHINE itself keeps its
invariants under a crash/retry/timeout environment.

Grammar (authoring guide in the README "Protocol contracts" section; every
line of the block starts ``# protocol:``)::

    machine <name> field=<f> [states=<CONST>] init=<state>
    states: a | b | c                 explicit vocabulary (or states=CONST,
                                      a module-level tuple of strings —
                                      the single source of truth)
    <from> -> <to> | <to>             the legal transition relation
    var <v>: <lo>..<hi> = <init>      bounded model variable (saturating)
    action <n>: <from> -> <to> [requires <cond>] [effect <v> += 1, ...]
    env <n>: ...                      same shape; an ENVIRONMENT event
                                      (crash, TTL firing, duplicated
                                      delivery) the model composes in
    invariant <n>: <cond>             safety: must hold in every reachable
                                      composite state (checked by MODL)
    progress <n>: <cond>              no reachable state satisfying <cond>
                                      may be stuck (zero enabled actions)

``field=`` selects the AST checking mode: a plain name checks both
``self.<f>`` attribute and ``rec["<f>"]`` dict-record accesses; ``<f>[]``
is the keyed-counter form (state names are the subscript keys of
``self.<f>``, vocabulary/coverage checked, no transition semantics); ``-``
declares a model-only machine (no literal state field in the code — the
machine exists for MODL).  ``<cond>`` is ``atom (and atom)*`` /
``... or ...`` / ``A implies B`` over atoms ``term op value`` with term
``state`` or a declared var, op one of ``== != < <= > >=``.

The AST checker resolves every assignment/compare on a declared state
field — including sink methods (a method assigning the field from its own
parameter makes ``self._transition("open")`` a checked write at the call
site) and accessor aliases (``st = self.mode()`` narrows later branches
when every return of ``mode`` is the bare field) — and flags undeclared
state names, undeclared transitions (the write's from-set is narrowed by
enclosing/early-return guards), init drift, and vocabulary members the
class never uses (coverage, both directions).

A second standalone form gates closed reason taxonomies::

    # protocol: taxonomy <CONST> producers=<fn>,<fn> scope=<path-prefix>

Every string literal fed to (or returned by) a producer inside the scope
must be a member, and — when the full scope is loaded, so the check is
sound under --changed-only — every member must be produced somewhere.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field as dc_field

from .core import Context, Finding, SourceFile

CODES = {
    "PROT": "code contradicts a # protocol: contract — undeclared state/transition, init drift, or a closed vocabulary not covered both directions",
}

# Machine contracts live in the same file as their class; taxonomy coverage
# only runs when the declared scope is fully loaded.  Both are sound on a
# partial (--changed-only) context.
FILE_SCOPED = True

_PROT_RE = re.compile(r"#\s*protocol:\s?(.*)$")

_KEYWORDS = ("states:", "var ", "action ", "env ", "invariant ", "progress ")


# -- spec model ---------------------------------------------------------------


@dataclass(frozen=True)
class Var:
    name: str
    lo: int
    hi: int
    init: int


@dataclass(frozen=True)
class Action:
    name: str
    frm: str  # state name or "*" (any)
    to: str  # state name or "*" (stay)
    requires: tuple | None
    effects: tuple  # ((var, op, value), ...) with op in {"=", "+=", "-="}
    env: bool
    line: int


@dataclass
class MachineSpec:
    name: str
    rel: str
    line: int
    cls_name: str
    field: str | None  # None => model-only (field=-)
    keyed: bool  # field=<f>[] keyed-counter form
    states: tuple = ()
    states_const: str | None = None
    init: str = ""
    edges: dict = dc_field(default_factory=dict)  # frm -> set of to
    vars: tuple = ()
    actions: tuple = ()
    invariants: tuple = ()  # ((name, cond, line), ...)
    progress: tuple = ()


@dataclass(frozen=True)
class TaxonomySpec:
    const: str
    rel: str
    line: int
    members: tuple
    producers: tuple
    scope: str


# -- condition mini-language --------------------------------------------------

_ATOM_RE = re.compile(r"^([\w-]+)\s*(==|!=|<=|>=|<|>)\s*([\w-]+)$")


def parse_cond(text: str, states: tuple, var_names: set) -> tuple:
    """``A implies B`` over or/and chains of ``term op value`` atoms."""
    t = text.strip()
    if " implies " in t:
        lhs, rhs = t.split(" implies ", 1)
        return ("implies", parse_cond(lhs, states, var_names), parse_cond(rhs, states, var_names))
    if " or " in t:
        return ("or", tuple(parse_cond(p, states, var_names) for p in t.split(" or ")))
    if " and " in t:
        return ("and", tuple(parse_cond(p, states, var_names) for p in t.split(" and ")))
    m = _ATOM_RE.match(t)
    if not m:
        raise ValueError(f"bad condition atom {t!r}")
    term, op, value = m.group(1), m.group(2), m.group(3)
    if term == "state":
        if op not in ("==", "!="):
            raise ValueError(f"state only compares ==/!= (got {op!r})")
        if value not in states:
            raise ValueError(f"condition names unknown state {value!r}")
        return ("atom", term, op, value)
    if term not in var_names:
        raise ValueError(f"condition names unknown var {term!r}")
    if not re.fullmatch(r"-?\d+", value):
        raise ValueError(f"var {term!r} compares against an int (got {value!r})")
    return ("atom", term, op, int(value))


def eval_cond(cond: tuple, state: str, env: dict) -> bool:
    kind = cond[0]
    if kind == "implies":
        return (not eval_cond(cond[1], state, env)) or eval_cond(cond[2], state, env)
    if kind == "or":
        return any(eval_cond(c, state, env) for c in cond[1])
    if kind == "and":
        return all(eval_cond(c, state, env) for c in cond[1])
    _, term, op, value = cond
    lhs = state if term == "state" else env[term]
    return {
        "==": lhs == value,
        "!=": lhs != value,
        "<": lhs < value,
        "<=": lhs <= value,
        ">": lhs > value,
        ">=": lhs >= value,
    }[op]


# -- contract collection ------------------------------------------------------


def _module_str_tuple(tree: ast.Module, name: str) -> tuple | None:
    """Module-level ``NAME = ("a", "b", ...)`` -> its members, else None."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, (ast.Tuple, ast.List)):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    vals = [
                        e.value
                        for e in node.value.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    ]
                    if len(vals) == len(node.value.elts):
                        return tuple(vals)
    return None


def _protocol_block(sf: SourceFile, node: ast.ClassDef) -> list:
    """(lineno, payload) for every ``# protocol:`` line in the comment block
    directly above the class (decorators may sit between), top-down."""
    start = min([node.lineno] + [d.lineno for d in node.decorator_list])
    i = start - 2  # 0-indexed line above the def/decorator block
    block: list = []
    while i >= 0 and sf.lines[i].strip().startswith("#"):
        block.append((i + 1, sf.lines[i].strip()))
        i -= 1
    out = []
    for lineno, text in reversed(block):  # top-down
        m = _PROT_RE.match(text)
        if m:
            out.append((lineno, m.group(1).strip()))
    return out


def _parse_effects(text: str, var_names: set) -> tuple:
    effects = []
    for part in text.split(","):
        m = re.match(r"^\s*([\w-]+)\s*(\+=|-=|=)\s*(-?\d+)\s*$", part)
        if not m:
            raise ValueError(f"bad effect {part.strip()!r}")
        var, op, val = m.group(1), m.group(2), int(m.group(3))
        if var not in var_names:
            raise ValueError(f"effect names unknown var {var!r}")
        effects.append((var, op, val))
    return tuple(effects)


def _parse_action(payload: str, env: bool, lineno: int, states: tuple, var_names: set) -> Action:
    head, _, rest = payload.partition(":")
    name = head.split(None, 1)[1].strip()
    if not name:
        raise ValueError("action needs a name")
    rest = rest.strip()
    eff_txt = None
    if " effect " in rest:
        rest, eff_txt = rest.split(" effect ", 1)
    req_txt = None
    if " requires " in rest:
        rest, req_txt = rest.split(" requires ", 1)
    m = re.match(r"^([\w*-]+)\s*->\s*([\w*-]+)$", rest.strip())
    if not m:
        raise ValueError(f"action {name!r} needs '<from> -> <to>'")
    frm, to = m.group(1), m.group(2)
    for s in (frm, to):
        if s != "*" and s not in states:
            raise ValueError(f"action {name!r} names unknown state {s!r}")
    requires = parse_cond(req_txt, states, var_names) if req_txt else None
    effects = _parse_effects(eff_txt, var_names) if eff_txt else ()
    return Action(name=name, frm=frm, to=to, requires=requires, effects=effects, env=env, line=lineno)


def parse_machine(payloads: list, sf: SourceFile, cls: ast.ClassDef) -> tuple:
    """The ``# protocol:`` block of one class -> (MachineSpec | None,
    findings).  Header errors drop the machine; line errors drop the line."""
    findings: list[Finding] = []
    first_line = payloads[0][0]
    head = payloads[0][1]
    m = re.match(r"^machine\s+([\w-]+)\s+(.*)$", head)
    if not m:
        findings.append(
            Finding("PROT", sf.rel, first_line, f"protocol block on '{cls.name}' must open with 'machine <name> ...'")
        )
        return None, findings
    name, kv_txt = m.group(1), m.group(2)
    kv = {}
    for tok in kv_txt.split():
        if "=" not in tok:
            findings.append(Finding("PROT", sf.rel, first_line, f"machine '{name}': bad token {tok!r} (want key=value)"))
            return None, findings
        k, v = tok.split("=", 1)
        kv[k] = v
    unknown = set(kv) - {"field", "states", "init"}
    if unknown or "field" not in kv or "init" not in kv:
        findings.append(
            Finding("PROT", sf.rel, first_line, f"machine '{name}': header needs field= and init= (optional states=CONST)")
        )
        return None, findings

    field_txt = kv["field"]
    keyed = field_txt.endswith("[]")
    fld = None if field_txt == "-" else (field_txt[:-2] if keyed else field_txt)

    # Two-phase: gather raw lines, resolve the vocabulary, then validate.
    explicit_states: tuple | None = None
    raw: list = []
    for lineno, payload in payloads[1:]:
        if payload.startswith("states:"):
            explicit_states = tuple(s.strip() for s in payload[len("states:"):].split("|") if s.strip())
        else:
            raw.append((lineno, payload))

    states_const = kv.get("states")
    states: tuple | None = explicit_states
    if states_const is not None:
        resolved = _module_str_tuple(sf.tree, states_const)
        if resolved is None:
            findings.append(
                Finding(
                    "PROT", sf.rel, first_line,
                    f"machine '{name}': states={states_const} does not resolve to a module-level tuple of strings",
                )
            )
            return None, findings
        if explicit_states is not None and explicit_states != resolved:
            findings.append(
                Finding(
                    "PROT", sf.rel, first_line,
                    f"machine '{name}': explicit states differ from {states_const} = {resolved}",
                )
            )
            return None, findings
        states = resolved
    if not states:
        findings.append(Finding("PROT", sf.rel, first_line, f"machine '{name}': no state vocabulary (states: or states=CONST)"))
        return None, findings
    if kv["init"] not in states:
        findings.append(Finding("PROT", sf.rel, first_line, f"machine '{name}': init={kv['init']} is not a declared state"))
        return None, findings

    spec = MachineSpec(
        name=name, rel=sf.rel, line=first_line, cls_name=cls.name,
        field=fld, keyed=keyed, states=states, states_const=states_const, init=kv["init"],
    )
    edges: dict = {}
    vars_: list = []
    actions: list = []
    invariants: list = []
    progress: list = []
    var_names: set = set()
    edge_re = re.compile(r"^([\w-]+)\s*->\s*([\w|\s-]+)$")

    # vars first: actions/invariants reference them regardless of line order
    for lineno, payload in raw:
        if payload.startswith("var "):
            m2 = re.match(r"^var\s+([\w-]+)\s*:\s*(-?\d+)\s*\.\.\s*(-?\d+)\s*=\s*(-?\d+)\s*$", payload)
            if not m2:
                findings.append(Finding("PROT", sf.rel, lineno, f"machine '{name}': bad var line {payload!r}"))
                continue
            v = Var(m2.group(1), int(m2.group(2)), int(m2.group(3)), int(m2.group(4)))
            if not (v.lo <= v.init <= v.hi):
                findings.append(Finding("PROT", sf.rel, lineno, f"machine '{name}': var {v.name} init outside {v.lo}..{v.hi}"))
                continue
            vars_.append(v)
            var_names.add(v.name)

    for lineno, payload in raw:
        try:
            if payload.startswith("var "):
                continue
            if payload.startswith(("action ", "env ")):
                a = _parse_action(payload, payload.startswith("env "), lineno, states, var_names)
                actions.append(a)
            elif payload.startswith("invariant "):
                m2 = re.match(r"^invariant\s+([\w-]+)\s*:\s*(.+)$", payload)
                if not m2:
                    raise ValueError(f"bad invariant line {payload!r}")
                invariants.append((m2.group(1), parse_cond(m2.group(2), states, var_names), lineno))
            elif payload.startswith("progress "):
                m2 = re.match(r"^progress\s+([\w-]+)\s*:\s*(.+)$", payload)
                if not m2:
                    raise ValueError(f"bad progress line {payload!r}")
                progress.append((m2.group(1), parse_cond(m2.group(2), states, var_names), lineno))
            else:
                m2 = edge_re.match(payload)
                if not m2:
                    raise ValueError(f"unrecognized protocol line {payload!r}")
                frm = m2.group(1)
                tos = [t.strip() for t in m2.group(2).split("|")]
                if frm not in states or any(t not in states for t in tos):
                    raise ValueError(f"transition line names unknown state: {payload!r}")
                edges.setdefault(frm, set()).update(tos)
        except ValueError as e:
            findings.append(Finding("PROT", sf.rel, lineno, f"machine '{name}': {e}"))

    # Spec self-consistency: every action edge must lie inside the declared
    # relation (wildcards and self-loops excepted) — the model can never
    # legitimize a transition the relation forbids.
    for a in actions:
        if a.frm != "*" and a.to != "*" and a.frm != a.to and a.to not in edges.get(a.frm, set()):
            findings.append(
                Finding(
                    "PROT", sf.rel, a.line,
                    f"machine '{name}': action '{a.name}' takes undeclared transition {a.frm} -> {a.to}",
                )
            )

    spec.edges = edges
    spec.vars = tuple(vars_)
    spec.actions = tuple(actions)
    spec.invariants = tuple(invariants)
    spec.progress = tuple(progress)
    return spec, findings


def collect_machines(sf: SourceFile) -> tuple:
    """Every ``# protocol: machine`` contract in the file ->
    ([(MachineSpec, ClassDef)], findings)."""
    out: list = []
    findings: list[Finding] = []
    if sf.tree is None or "# protocol:" not in sf.text:
        return out, findings
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        payloads = _protocol_block(sf, node)
        if not payloads:
            continue
        spec, errs = parse_machine(payloads, sf, node)
        findings.extend(errs)
        if spec is not None:
            out.append((spec, node))
    return out, findings


def _comment_lines(sf: SourceFile) -> list:
    """(lineno, text) for every real COMMENT token — a grammar example in a
    docstring must not parse as a contract."""
    out: list = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(sf.text).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def collect_taxonomies(sf: SourceFile) -> tuple:
    """Every standalone ``# protocol: taxonomy`` comment -> (specs, findings)."""
    out: list = []
    findings: list[Finding] = []
    if sf.tree is None or "# protocol:" not in sf.text:
        return out, findings
    for lineno, line in _comment_lines(sf):
        m = _PROT_RE.match(line.strip())
        if not m or not m.group(1).strip().startswith("taxonomy "):
            continue
        m2 = re.match(r"^taxonomy\s+(\w+)\s+producers=([\w,-]+)\s+scope=(\S+)$", m.group(1).strip())
        if not m2:
            findings.append(
                Finding("PROT", sf.rel, lineno, "bad taxonomy line (want: taxonomy CONST producers=a,b scope=path)")
            )
            continue
        const, producers, scope = m2.group(1), tuple(p for p in m2.group(2).split(",") if p), m2.group(3)
        members = _module_str_tuple(sf.tree, const)
        if members is None:
            findings.append(
                Finding("PROT", sf.rel, lineno, f"taxonomy {const}: no module-level tuple of strings with that name")
            )
            continue
        out.append(TaxonomySpec(const=const, rel=sf.rel, line=lineno, members=members, producers=producers, scope=scope))
    return out, findings


# -- AST transition checker ---------------------------------------------------


def _is_self_attr(node: ast.expr, attr: str) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and node.attr == attr
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _is_field_read(node: ast.expr, fld: str) -> bool:
    """``self.<fld>`` or ``<expr>["<fld>"]`` (the dict-record form)."""
    if _is_self_attr(node, fld):
        return True
    return (
        isinstance(node, ast.Subscript)
        and isinstance(node.slice, ast.Constant)
        and node.slice.value == fld
    )


def _target_value_pairs(node: ast.Assign) -> list:
    pairs = []
    for t in node.targets:
        if (
            isinstance(t, ast.Tuple)
            and isinstance(node.value, ast.Tuple)
            and len(t.elts) == len(node.value.elts)
        ):
            pairs.extend(zip(t.elts, node.value.elts))
        else:
            pairs.append((t, node.value))
    return pairs


def _terminates(body: list) -> bool:
    return bool(body) and isinstance(body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _ClassChecker:
    """Checks one annotated class body against its MachineSpec."""

    def __init__(self, spec: MachineSpec, cls: ast.ClassDef, sf: SourceFile):
        self.spec = spec
        self.cls = cls
        self.sf = sf
        self.findings: list[Finding] = []
        self.mentioned: set = set()
        self.fns = [n for n in cls.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self.sinks: dict = {}  # method name -> positional index after self
        self.accessors: set = set()
        if spec.field is not None and not spec.keyed:
            self._find_sinks_and_accessors()

    def emit(self, lineno: int, message: str) -> None:
        self.findings.append(Finding("PROT", self.sf.rel, lineno, message))

    def _find_sinks_and_accessors(self) -> None:
        fld = self.spec.field
        for fn in self.fns:
            params = [a.arg for a in fn.args.args]
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for tgt, val in _target_value_pairs(node):
                        if _is_field_read(tgt, fld) and isinstance(val, ast.Name) and val.id in params:
                            idx = params.index(val.id) - 1  # after self
                            if idx >= 0:
                                self.sinks[fn.name] = idx
            rets = [n for n in ast.walk(fn) if isinstance(n, ast.Return)]
            if rets and all(r.value is not None and _is_self_attr(r.value, fld) for r in rets):
                self.accessors.add(fn.name)

    # -- the walk ------------------------------------------------------------

    def check(self) -> list[Finding]:
        if self.spec.keyed:
            self._check_keyed()
        else:
            for fn in self.fns:
                self._visit_fn(fn)
        for s in self.spec.states:
            if s not in self.mentioned:
                src = self.spec.states_const or "the states line"
                self.emit(
                    self.spec.line,
                    f"machine '{self.spec.name}': state '{s}' declared in {src} is never used by {self.cls.name}",
                )
        return self.findings

    def _check_keyed(self) -> None:
        base = self.spec.field
        for node in ast.walk(self.cls):
            if (
                isinstance(node, ast.Subscript)
                and _is_self_attr(node.value, base)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                self._mention(node.slice.value, node.lineno)

    def _mention(self, state: str, lineno: int) -> None:
        self.mentioned.add(state)
        if state not in self.spec.states:
            self.emit(
                lineno,
                f"machine '{self.spec.name}': '{state}' is not a declared state of {self.spec.cls_name}",
            )

    def _visit_fn(self, fn) -> None:
        self._block(fn.body, None, set(), fn)

    def _block(self, stmts: list, fromset, aliases: set, fn) -> None:
        for s in stmts:
            # Own expressions: compares, sink calls, dict-literal inits.
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, fromset, aliases, fn)
            if isinstance(s, ast.Assign):
                self._handle_assign(s, fromset, aliases, fn)
            elif isinstance(s, ast.AugAssign):
                pass  # numeric bumps; keyed form handled separately
            elif isinstance(s, ast.If):
                pos, neg = self._narrow(s.test, aliases)
                self._block(s.body, _inter(fromset, pos, self.spec.states), set(aliases), fn)
                self._block(s.orelse, _inter(fromset, neg, self.spec.states), set(aliases), fn)
                if _terminates(s.body) and not s.orelse:
                    # early-return guard: the rest of the block runs only
                    # when the test was false
                    fromset = _inter(fromset, neg, self.spec.states)
            elif isinstance(s, (ast.For, ast.AsyncFor, ast.While)):
                # a loop body may re-enter with a different state
                self._block(s.body, None, set(aliases), fn)
                self._block(s.orelse, None, set(aliases), fn)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                self._block(s.body, fromset, set(aliases), fn)
            elif isinstance(s, ast.Try):
                self._block(s.body, fromset, set(aliases), fn)
                for h in s.handlers:
                    self._block(h.body, None, set(aliases), fn)
                self._block(s.orelse, fromset, set(aliases), fn)
                self._block(s.finalbody, None, set(aliases), fn)
            elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._block(s.body, None, set(), s)

    def _handle_assign(self, s: ast.Assign, fromset, aliases: set, fn) -> None:
        fld = self.spec.field
        params = [a.arg for a in fn.args.args] if hasattr(fn.args, "args") else []
        for tgt, val in _target_value_pairs(s):
            if _is_field_read(tgt, fld):
                if isinstance(val, ast.Constant) and isinstance(val.value, str):
                    self._check_write(val.value, s.lineno, fromset, fn)
                elif isinstance(val, ast.Name) and val.id in params:
                    pass  # the sink definition itself
                # non-constant write: unknown, conservatively quiet
            elif isinstance(tgt, ast.Name):
                if _is_field_read(val, fld) or self._is_accessor_call(val):
                    aliases.add(tgt.id)
                else:
                    aliases.discard(tgt.id)

    def _is_accessor_call(self, node) -> bool:
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in self.accessors
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        )

    def _is_field_expr(self, node, aliases: set) -> bool:
        if _is_field_read(node, self.spec.field) or self._is_accessor_call(node):
            return True
        return isinstance(node, ast.Name) and node.id in aliases

    def _check_write(self, to: str, lineno: int, fromset, fn) -> None:
        spec = self.spec
        self._mention(to, lineno)
        if to not in spec.states:
            return
        if fn.name == "__init__":
            if to != spec.init:
                self.emit(lineno, f"machine '{spec.name}': __init__ sets '{to}' but init={spec.init}")
            return
        froms = sorted(fromset) if fromset is not None else sorted(spec.states)
        for frm in froms:
            if frm != to and to not in spec.edges.get(frm, set()):
                self.emit(lineno, f"machine '{spec.name}': undeclared transition {frm} -> {to}")

    def _check_init_literal(self, value: str, lineno: int) -> None:
        self._mention(value, lineno)
        if value in self.spec.states and value != self.spec.init:
            self.emit(
                lineno,
                f"machine '{self.spec.name}': record created in state '{value}' but init={self.spec.init}",
            )

    def _scan_expr(self, expr: ast.expr, fromset, aliases: set, fn) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(self._is_field_expr(x, aliases) for x in sides):
                    for x in sides:
                        if isinstance(x, ast.Constant) and isinstance(x.value, str):
                            self._mention(x.value, node.lineno)
                        elif isinstance(x, (ast.Tuple, ast.List)):
                            for e in x.elts:
                                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                                    self._mention(e.value, node.lineno)
            elif isinstance(node, ast.Call):
                f = node.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr in self.sinks
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                ):
                    idx = self.sinks[f.attr]
                    if idx < len(node.args) and isinstance(node.args[idx], ast.Constant):
                        v = node.args[idx].value
                        if isinstance(v, str):
                            self._check_write(v, node.lineno, fromset, fn)
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant)
                        and k.value == self.spec.field
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                    ):
                        self._check_init_literal(v.value, node.lineno)

    # -- guard narrowing -----------------------------------------------------

    def _narrow(self, test: ast.expr, aliases: set) -> tuple:
        """(states implied when true, states implied when false); None =
        no information."""
        vocab = set(self.spec.states)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            pos, neg = self._narrow(test.operand, aliases)
            return neg, pos
        if isinstance(test, ast.BoolOp):
            parts = [self._narrow(v, aliases) for v in test.values]
            if isinstance(test.op, ast.And):
                pos = None
                for p, _ in parts:
                    if p is not None:
                        pos = p if pos is None else (pos & p)
                return pos, None
            neg = None
            for _, n in parts:
                if n is not None:
                    neg = n if neg is None else (neg & n)
            return None, neg
        if isinstance(test, ast.Compare) and len(test.ops) == 1:
            left, op, right = test.left, test.ops[0], test.comparators[0]
            if not self._is_field_expr(left, aliases):
                return None, None
            if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(right, ast.Constant) and isinstance(right.value, str):
                s = {right.value} & vocab
                if not s:
                    return None, None
                return (s, vocab - s) if isinstance(op, ast.Eq) else (vocab - s, s)
            if isinstance(op, (ast.In, ast.NotIn)) and isinstance(right, (ast.Tuple, ast.List)):
                s = {e.value for e in right.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)} & vocab
                if not s:
                    return None, None
                return (s, vocab - s) if isinstance(op, ast.In) else (vocab - s, s)
        return None, None


def _inter(a, b, states) -> set | None:
    if a is None and b is None:
        return None
    if a is None:
        return set(b)
    if b is None:
        return set(a)
    return set(a) & set(b)


# -- taxonomy checking --------------------------------------------------------


def _literal_args(node: ast.expr) -> list:
    """String constants a producer argument can evaluate to: a bare
    constant, the branches of a conditional, or ``x or "default"``."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else []
    if isinstance(node, ast.IfExp):
        return _literal_args(node.body) + _literal_args(node.orelse)
    if isinstance(node, ast.BoolOp):
        out = []
        for v in node.values:
            out.extend(_literal_args(v))
        return out
    return []


def _check_taxonomies(taxes: list, ctx: Context) -> list:
    """All taxonomies at once — ONE ast.walk per in-scope file (the
    taxonomies all scope tpu_scheduler, so per-taxonomy walks would
    re-traverse the whole tree once per declaration)."""
    findings: list[Finding] = []
    # (tax, scope prefix, member set, used set) per declaration.
    infos = [(tax, tax.scope.rstrip("/") + "/", set(tax.members), set()) for tax in taxes]
    for f in ctx.parsed():
        in_scope = [
            row
            for row in infos
            if (f.rel.startswith(row[1]) or f.rel == row[0].scope)
            # a producer call/def needs the literal name in the source —
            # the text probe skips walking the (many) files that lack all
            # of them
            and any(p in f.text for p in row[0].producers)
        ]
        if not in_scope:
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                name = fn.id if isinstance(fn, ast.Name) else (fn.attr if isinstance(fn, ast.Attribute) else None)
                if name is None or not node.args:
                    continue
                for tax, _, members, used in in_scope:
                    if name not in tax.producers:
                        continue
                    for lit in _literal_args(node.args[0]):
                        used.add(lit)
                        if lit not in members:
                            findings.append(
                                Finding(
                                    "PROT", f.rel, node.lineno,
                                    f"'{lit}' passed to {name}() is not in {tax.const} ({tax.rel})",
                                )
                            )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for tax, _, members, used in in_scope:
                    if node.name not in tax.producers:
                        continue
                    for ret in ast.walk(node):
                        if isinstance(ret, ast.Return) and ret.value is not None:
                            for lit in _literal_args(ret.value):
                                used.add(lit)
                                if lit not in members:
                                    findings.append(
                                        Finding(
                                            "PROT", f.rel, ret.lineno,
                                            f"'{lit}' returned by {node.name}() is not in {tax.const} ({tax.rel})",
                                        )
                                    )
    # Coverage direction only when the whole scope is loaded (sound under
    # --changed-only: a partial context skips it rather than lying).
    loaded = {f.rel for f in ctx.files}
    for tax, _, _, used in infos:
        scope_dir = ctx.root / tax.scope
        if not scope_dir.is_dir():
            continue
        on_disk = {p.relative_to(ctx.root).as_posix() for p in scope_dir.rglob("*.py")}
        if on_disk <= loaded:
            for m in tax.members:
                if m not in used:
                    findings.append(
                        Finding(
                            "PROT", tax.rel, tax.line,
                            f"taxonomy {tax.const}: member '{m}' is never produced by {'/'.join(tax.producers)} under {tax.scope}",
                        )
                    )
    return findings


# -- pass entry ---------------------------------------------------------------


def run(ctx: Context) -> list:
    findings: list[Finding] = []
    all_taxes: list = []
    for f in ctx.parsed():
        machines, errs = collect_machines(f)
        findings.extend(errs)
        for spec, cls in machines:
            if spec.field is not None:
                findings.extend(_ClassChecker(spec, cls, f).check())
        taxes, errs = collect_taxonomies(f)
        findings.extend(errs)
        all_taxes.extend(taxes)
    if all_taxes:
        findings.extend(_check_taxonomies(all_taxes, ctx))
    return findings
