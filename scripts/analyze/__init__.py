"""Invariant-aware static analysis suite — the repo's whole lint policy.

A zero-dependency, stdlib-AST analysis package: one parse per file, shared
by every pass (the monolithic ``scripts/lint.py`` re-walked nothing but also
shared nothing — every new rule meant another ad-hoc loop).  ``scripts/
lint.py`` survives as a thin shim so existing invocations keep working.

Passes (each a module in this package; the rule catalogue is drift-gated
into README.md by the ANLZ pass):

  hygiene      — E999 W291 W191 E711 E712 E722 E741 B006 F841 F401 F822
  exports      — DEAD (exported-but-referenced-nowhere symbols)
  catalogues   — METR SIMC ANLZ RESC (README drift gates)
  excp         — EXCP (the requeue failure-class taxonomy stays closed:
                 classifier ↔ backoff policies ↔ metric row ↔ README table)
  locks        — THRD (lock discipline: ``# guarded-by:`` attributes,
                 ``# holds-lock:`` contracts, lock-order cycle detection)
  jitpure      — JAXP (no host syncs / tracer branches inside jit)
  determinism  — DTRM (sim/ may only consume the clock and seeded rng)
  shapes       — SHPE (``# shape:`` contracts abstract-interpreted over the
                 tensor pipeline: dims, broadcasts, axes, dtype promotion)

Each pass declares ``FILE_SCOPED``: whether it is sound on a partial file
set (the driver's ``--changed-only`` pre-commit fast path runs only those;
cross-file rules like DEAD/EXCP need the full context).

Findings are compared against ``baseline.json`` (pinned pre-existing
findings, each with a reason); the driver fails on any NEW finding and on
any STALE baseline entry — the baseline can only shrink.
"""

from .core import Context, Finding, SourceFile  # noqa: F401 — package surface
