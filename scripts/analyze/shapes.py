"""SHPE — annotation-driven shape/dtype abstract interpretation over the
pods×nodes tensor pipeline.

The whole hot path (ops/masks.py, ops/score.py, ops/assign.py, both
backends, parallel/sharded.py) lives by implicit ``[P, N]`` shape and dtype
conventions that nothing checked statically: a transposed mask or a silent
bool→float promotion only surfaced as a wrong placement or an XLA error
deep inside a jit trace.  This pass makes the conventions machine-checked.

A function declares its tensor contract in a ``# shape:`` comment directly
above its ``def`` (decorators may sit between; long contracts continue onto
following comment lines until the parentheses balance)::

    # shape: (pods_mask: [P, N] bool, scores: [P, N] f32) -> [P] i32
    def pick(pods_mask, scores):
        ...

Grammar (the authoring guide lives in the README "Shape contracts"
section)::

    contract := '(' arg ':' spec (',' arg ':' spec)* ')' '->' ret
    spec     := '[' dim (',' dim)* ']' dtype     a tensor
              | 'scalar' dtype | int|float|bool  a rank-0 value
              | obj | any | dict | fn | str      opaque (unchecked)
    dim      := symbol (P, N, B, R, ...) | integer | '?'
    dtype    := bool | i8..i64 | u8..u64 | f16|bf16|f32|f64 | num | any
    ret      := spec | '(' spec (',' spec)* ')'  tuple returns

Parameters omitted from the contract are unchecked.  Symbols are scoped to
one contract; a scalar parameter's *name* used in an ``xp.zeros((p_pad,
t_pad))`` shape tuple becomes that symbolic dim, so allocation shapes check
against the declared return.

The interpreter propagates symbolic dims and dtypes through elementwise
arithmetic (with full NumPy broadcasting), comparisons, matmuls (inner-dim
check), ``where``/``select``, reductions (``axis=`` validated against the
symbolic rank), ``reshape``/``transpose``/``concatenate``/``stack``,
indexing (including ``None`` newaxis, ``...``, literal bounds checks), and
``.astype``.  Calls to other annotated functions — resolved same-module
first, then through from-imports across every analyzed module, the JAXP
name-resolution pattern — unify the callee's symbols against the caller's
dims and flow the declared return back, so a transposed ``[N, P]`` argument
is caught at the call site.  Anything unknown stays unknown and never
flags: the pass is deliberately conservative, findings mean a *declared*
contract is contradicted.

Findings:
  • broadcast conflict      — ``[P, N]`` combined with ``[N, P]``
  • matmul inner mismatch   — ``[P, L] @ [N, L]`` (forgot the ``.T``)
  • reduction axis          — ``axis=`` outside the symbolic rank
  • index out of bounds     — literal index past a literal dim
  • dtype promotion         — bool masks leaking into arithmetic, int/float
                              array mixes without an explicit ``.astype``
  • return drift            — computed shape/dtype contradicts ``-> ...``
  • contract rot            — malformed spec, or a parameter the function
                              no longer has
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass

from .core import Context, Finding, SourceFile

CODES = {
    "SHPE": "a tensor op contradicts a declared # shape: contract — transposed dims, bad broadcast/axis, or dtype promotion",
}

# Per-file contracts + same-file/from-import resolution: a partial
# (--changed-only) run checks what it loads and never false-positives.
FILE_SCOPED = True

_DTYPE_TOKENS = {
    "bool": "bool",
    "i8": "i8", "i16": "i16", "i32": "i32", "i64": "i64",
    "u8": "u8", "u16": "u16", "u32": "u32", "u64": "u64",
    "f16": "f16", "bf16": "bf16", "f32": "f32", "f64": "f64",
    "num": None, "any": None,
}

# numpy/jnp attribute name -> canonical dtype token
_NP_DTYPES = {
    "bool_": "bool", "bool": "bool",
    "int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64", "intp": "i64",
    "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
    "float16": "f16", "bfloat16": "bf16", "float32": "f32", "float64": "f64",
}


def _family(dtype: str | None) -> str | None:
    if dtype is None:
        return None
    if dtype == "bool":
        return "bool"
    return "float" if dtype.startswith(("f", "bf")) else "int"


@dataclass(frozen=True)
class AV:
    """Abstract value: symbolic dims (str symbol | int | None-unknown per
    axis; the whole tuple None when the shape is unknown) + dtype token."""

    dims: tuple | None
    dtype: str | None

    @property
    def known_shape(self) -> bool:
        return self.dims is not None

    def render(self) -> str:
        if self.dims is None:
            shape = "[?]"
        elif self.dims == ():
            shape = "scalar"
        else:
            shape = "[" + ", ".join("?" if d is None else str(d) for d in self.dims) + "]"
        return f"{shape} {self.dtype or 'any'}"


UNKNOWN = AV(None, None)


class _DtypeCtor:
    """``xp.float32`` / ``f32 = xp.float32`` — calling it makes a scalar."""

    def __init__(self, dtype: str):
        self.dtype = dtype


class _Tup:
    def __init__(self, items: list):
        self.items = items


# -- contract parsing --------------------------------------------------------

_CONTRACT_RE = re.compile(r"#\s*shape:\s*(.*)$")


@dataclass
class Contract:
    params: list  # [(name, AV | None-opaque)]
    ret: object  # AV | _Tup | None-opaque
    line: int


def _parse_spec(text: str):
    """One spec -> AV, or None for opaque.  Raises ValueError on nonsense."""
    t = text.strip()
    if not t:
        raise ValueError("empty spec")
    if t.startswith("["):
        end = t.index("]")
        dims_txt, dtype_txt = t[1:end], t[end + 1 :].strip()
        dims = []
        for d in dims_txt.split(","):
            d = d.strip()
            if not d:
                raise ValueError(f"empty dim in {text!r}")
            if d == "?":
                dims.append(None)
            elif re.fullmatch(r"-?\d+", d):
                dims.append(int(d))
            elif re.fullmatch(r"\w+", d):
                dims.append(d)
            else:
                raise ValueError(f"bad dim {d!r}")
        if dtype_txt not in _DTYPE_TOKENS:
            raise ValueError(f"unknown dtype {dtype_txt!r}")
        return AV(tuple(dims), _DTYPE_TOKENS[dtype_txt])
    if t.startswith("scalar"):
        dtype_txt = t[len("scalar") :].strip() or "any"
        if dtype_txt not in _DTYPE_TOKENS:
            raise ValueError(f"unknown dtype {dtype_txt!r}")
        return AV((), _DTYPE_TOKENS[dtype_txt])
    if t in ("int",):
        return AV((), "i64")
    if t in ("float",):
        return AV((), "f64")
    if t in ("bool",):
        return AV((), "bool")
    if t in ("obj", "any", "dict", "fn", "str", "bytes", "none"):
        return None
    raise ValueError(f"unknown spec {t!r}")


def _split_top(text: str, sep: str = ",") -> list[str]:
    out, depth, cur = [], 0, []
    for ch in text:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        if ch == sep and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _parse_contract(text: str, line: int) -> Contract:
    """``(a: SPEC, b: SPEC) -> SPEC`` (or ``-> (SPEC, SPEC)``)."""
    m = re.match(r"\s*\((.*)\)\s*->\s*(.*)$", text.strip(), re.DOTALL)
    if not m:
        raise ValueError("expected '(args) -> ret'")
    args_txt, ret_txt = m.group(1), m.group(2).strip()
    params = []
    if args_txt.strip():
        for part in _split_top(args_txt):
            if ":" not in part:
                raise ValueError(f"arg {part.strip()!r} missing ': spec'")
            name, spec = part.split(":", 1)
            params.append((name.strip(), _parse_spec(spec)))
    if ret_txt.startswith("(") and ret_txt.endswith(")"):
        ret = _Tup([_parse_spec(p) for p in _split_top(ret_txt[1:-1])])
    else:
        ret = _parse_spec(ret_txt)
    return Contract(params=params, ret=ret, line=line)


def _collect_contracts(f: SourceFile) -> dict[ast.FunctionDef, tuple[Contract | None, str | None]]:
    """fn-def -> (contract, parse-error).  The contract is the ``# shape:``
    comment block directly above the def/decorators (continuation comment
    lines are joined while parens stay unbalanced)."""
    out: dict[ast.FunctionDef, tuple[Contract | None, str | None]] = {}
    lines = f.lines
    for node in ast.walk(f.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        start = min([node.lineno] + [d.lineno for d in node.decorator_list])
        i = start - 2  # 0-indexed line above the def/decorator block
        block: list[tuple[int, str]] = []
        while i >= 0 and lines[i].strip().startswith("#"):
            block.append((i + 1, lines[i].strip()))
            i -= 1
        # block is bottom-up; find the # shape: opener closest to the def.
        for j, (lineno, text) in enumerate(block):
            m = _CONTRACT_RE.match(text)
            if not m:
                continue
            spec = m.group(1)
            # Continuations run DOWN the file from the opener: earlier
            # entries of the bottom-up block.  Keep joining until the
            # brackets balance AND the '->' arrow has appeared (the return
            # spec may start a fresh line after the args close).
            for k in range(j - 1, -1, -1):
                balanced = spec.count("(") + spec.count("[") <= spec.count(")") + spec.count("]")
                if balanced and "->" in spec:
                    break
                spec += " " + block[k][1].lstrip("#").strip()
            try:
                out[node] = (_parse_contract(spec, lineno), None)
            except ValueError as e:
                out[node] = (None, f"malformed shape contract for '{node.name}': {e}")
            break
    return out


# -- module index (imports, cross-module resolution) -------------------------


class _ModIndex:
    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.ns_bases: set[str] = {"xp"}  # array namespaces (np/jnp/lax/xp)
        self.from_imports: set[str] = set()
        tree = sf.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name in ("numpy", "jax.numpy"):
                        self.ns_bases.add(bound if a.asname else a.name.split(".")[0])
                    if a.name == "jax.numpy" and a.asname:
                        self.ns_bases.add(a.asname)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    self.from_imports.add(bound)
                    if node.module == "jax" and a.name in ("numpy", "lax"):
                        self.ns_bases.add(bound)
        self.ns_bases.update({"np", "jnp", "lax"} & self._bound_names(tree))

    @staticmethod
    def _bound_names(tree) -> set[str]:
        names: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    if a.name != "*":
                        names.add(a.asname or a.name.split(".")[0])
        return names


# -- shape algebra -----------------------------------------------------------


def _dim_eq(a, b) -> bool:
    return a is not None and b is not None and a == b


def _dim_conflict(a, b) -> bool:
    """True when two dims provably differ (and neither broadcasts)."""
    if a is None or b is None or a == 1 or b == 1:
        return False
    if isinstance(a, int) and isinstance(b, int):
        return a != b
    if isinstance(a, str) and isinstance(b, str):
        return a != b
    return False  # symbol vs literal: could coincide


def _broadcast(d1: tuple | None, d2: tuple | None) -> tuple[tuple | None, bool]:
    """NumPy broadcast of two dim tuples -> (result dims, conflict?)."""
    if d1 is None or d2 is None:
        return None, False
    r = max(len(d1), len(d2))
    a = (1,) * (r - len(d1)) + d1
    b = (1,) * (r - len(d2)) + d2
    out, conflict = [], False
    for x, y in zip(a, b):
        if _dim_conflict(x, y):
            conflict = True
            out.append(None)
        elif x == 1:
            out.append(y)
        elif y == 1:
            out.append(x)
        elif _dim_eq(x, y):
            out.append(x)
        else:
            out.append(None)
    return tuple(out), conflict


def _merge_dtype(a: str | None, b: str | None) -> str | None:
    if a == b:
        return a
    if a is None:
        return b
    if b is None:
        return a
    return None


_ARITH = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow)
_BITWISE = (ast.BitAnd, ast.BitOr, ast.BitXor)
_SHIFT = (ast.LShift, ast.RShift)

_REDUCTIONS = {
    "sum", "prod", "mean", "max", "min", "amax", "amin", "nanmax", "nanmin",
    "any", "all", "argmax", "argmin", "count_nonzero", "std", "var",
}
_SCAN_REDUCTIONS = {"cumsum", "cumprod"}  # keep rank, axis still validated
_ELEMENTWISE1 = {
    "abs", "absolute", "floor", "ceil", "exp", "log", "log2", "sqrt", "negative",
    "sign", "square", "tanh", "sin", "cos", "round", "rint", "clip", "nan_to_num",
    "stop_gradient", "copy",
}
_BOOL_OUT1 = {"isfinite", "isnan", "isinf", "logical_not", "signbit"}
_BINOP_FNS = {
    "minimum", "maximum", "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "power", "mod", "fmod", "hypot",
}
_LOGICAL2 = {"logical_and", "logical_or", "logical_xor"}


class _FnChecker:
    """Abstract-interprets one annotated function body against its contract."""

    def __init__(self, pass_ctx: "_PassCtx", idx: _ModIndex, fn: ast.FunctionDef, contract: Contract):
        self.p = pass_ctx
        self.idx = idx
        self.fn = fn
        self.contract = contract
        self.env: dict[str, object] = {}
        self.dtype_ctors: dict[str, str] = {}
        self.nested = {
            n for n in ast.walk(fn) if n is not fn and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.findings: list[Finding] = []
        # Symbols the contract itself declares: on return checks, a computed
        # dim carrying some OTHER name (a derived scalar like ``p_out``) is
        # an opaque identity, not a conflict with the declared symbol.
        self.contract_syms: set[str] = set()
        for _, spec in contract.params:
            if spec is not None and spec.dims:
                self.contract_syms.update(d for d in spec.dims if isinstance(d, str))
        rets = contract.ret.items if isinstance(contract.ret, _Tup) else [contract.ret]
        for r in rets:
            if isinstance(r, AV) and r.dims:
                self.contract_syms.update(d for d in r.dims if isinstance(d, str))

    # -- entry ---------------------------------------------------------------

    def check(self) -> list[Finding]:
        arg_names = [
            a.arg
            for a in (
                self.fn.args.posonlyargs + self.fn.args.args + self.fn.args.kwonlyargs
            )
        ]
        if self.fn.args.vararg:
            arg_names.append(self.fn.args.vararg.arg)
        if self.fn.args.kwarg:
            arg_names.append(self.fn.args.kwarg.arg)
        for name, spec in self.contract.params:
            if name not in arg_names:
                self.emit(
                    self.contract.line,
                    f"shape contract for '{self.fn.name}' names unknown parameter '{name}'",
                )
            elif spec is not None:
                self.env[name] = spec
        self.visit_block(self.fn.body)
        return self.findings

    def emit(self, lineno: int, message: str) -> None:
        self.findings.append(Finding("SHPE", self.idx.sf.rel, lineno, message))

    # -- statements ----------------------------------------------------------

    def visit_block(self, stmts) -> None:
        for s in stmts:
            self.visit_stmt(s)

    def visit_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            v = self.eval(s.value)
            for t in s.targets:
                self.bind(t, v)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.bind(s.target, self.eval(s.value))
        elif isinstance(s, ast.AugAssign):
            if isinstance(s.target, ast.Name):
                cur = self.env.get(s.target.id, UNKNOWN)
                self.env[s.target.id] = self.binop(cur, s.op, self.eval(s.value), s.lineno)
            else:
                self.eval(s.value)
        elif isinstance(s, ast.Return):
            v = self.eval(s.value) if s.value is not None else None
            self.check_return(v, s.lineno)
        elif isinstance(s, ast.If):
            self.eval(s.test)
            self.visit_block(s.body)
            self.visit_block(s.orelse)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self.eval(s.iter)
            # loop targets are data-dependent — unknown
            self.bind(s.target, UNKNOWN)
            self.visit_block(s.body)
            self.visit_block(s.orelse)
        elif isinstance(s, ast.While):
            self.eval(s.test)
            self.visit_block(s.body)
            self.visit_block(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.eval(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars, UNKNOWN)
            self.visit_block(s.body)
        elif isinstance(s, ast.Try):
            self.visit_block(s.body)
            for h in s.handlers:
                self.visit_block(h.body)
            self.visit_block(s.orelse)
            self.visit_block(s.finalbody)
        elif isinstance(s, ast.Expr):
            self.eval(s.value)
        elif isinstance(s, (ast.Raise, ast.Assert)):
            for sub in ast.iter_child_nodes(s):
                if isinstance(sub, ast.expr):
                    self.eval(sub)
        # nested defs, imports, pass, etc.: no propagation

    def bind(self, target, value) -> None:
        if isinstance(target, ast.Name):
            if isinstance(value, _DtypeCtor):
                self.dtype_ctors[target.id] = value.dtype
                self.env.pop(target.id, None)
            else:
                self.env[target.id] = value if isinstance(value, (AV, _Tup)) else UNKNOWN
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = value.items if isinstance(value, _Tup) else None
            for i, t in enumerate(target.elts):
                if isinstance(t, ast.Starred):
                    self.bind(t.value, UNKNOWN)
                    items = None  # positions after a star are unknowable
                    continue
                v = items[i] if items is not None and i < len(items) else UNKNOWN
                self.bind(t, v if v is not None else UNKNOWN)
        # attribute/subscript stores: no tracking

    def check_return(self, value, lineno: int) -> None:
        ret = self.contract.ret
        if ret is None:
            return
        if isinstance(ret, _Tup):
            if isinstance(value, _Tup):
                if len(value.items) != len(ret.items):
                    self.emit(
                        lineno,
                        f"'{self.fn.name}' returns {len(value.items)} values where the contract declares {len(ret.items)}",
                    )
                    return
                for got, want in zip(value.items, ret.items):
                    self.check_one_return(got, want, lineno)
            return
        self.check_one_return(value, ret, lineno)

    def check_one_return(self, got, want, lineno: int) -> None:
        if want is None or not isinstance(got, AV):
            return
        if want.dims is not None and got.dims is not None:
            if len(got.dims) != len(want.dims):
                self.emit(
                    lineno,
                    f"'{self.fn.name}' returns rank-{len(got.dims)} {got.render()} where the contract declares {want.render()}",
                )
                return
            for g, w in zip(got.dims, want.dims):
                if isinstance(g, str) and g not in self.contract_syms:
                    continue  # derived scalar name — opaque, not a conflict
                if _dim_conflict(g, w) and 1 not in (g, w):
                    self.emit(
                        lineno,
                        f"'{self.fn.name}' returns {got.render()} where the contract declares {want.render()}",
                    )
                    return
        gf, wf = _family(got.dtype), _family(want.dtype)
        if gf is not None and wf is not None and gf != wf:
            self.emit(
                lineno,
                f"'{self.fn.name}' returns dtype {got.dtype} where the contract declares {want.dtype}",
            )

    # -- expressions ---------------------------------------------------------

    def eval(self, e: ast.expr):
        if e is None:
            return UNKNOWN
        if isinstance(e, ast.Constant):
            if e.value is None:
                return None
            if isinstance(e.value, bool):
                return AV((), "bool")
            if isinstance(e.value, (int, float)):
                return AV((), None)  # weak scalar: adopts the array's dtype
            return UNKNOWN
        if isinstance(e, ast.Name):
            if e.id in self.dtype_ctors:
                return _DtypeCtor(self.dtype_ctors[e.id])
            v = self.env.get(e.id, UNKNOWN)
            return v
        if isinstance(e, ast.Attribute):
            return self.eval_attribute(e)
        if isinstance(e, ast.Subscript):
            return self.eval_subscript(e)
        if isinstance(e, ast.Call):
            return self.eval_call(e)
        if isinstance(e, ast.BinOp):
            return self.binop(self.eval(e.left), e.op, self.eval(e.right), e.lineno)
        if isinstance(e, ast.UnaryOp):
            v = self.eval(e.operand)
            if isinstance(e.op, ast.Not):
                return AV((), "bool")
            return v if isinstance(v, AV) else UNKNOWN
        if isinstance(e, ast.Compare):
            operands = [self.eval(e.left)] + [self.eval(c) for c in e.comparators]
            dims = None
            ok = True
            for op, (a, b) in zip(e.ops, zip(operands, operands[1:])):
                if isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)):
                    ok = False
                    continue
                if isinstance(a, AV) and isinstance(b, AV):
                    d, conflict = _broadcast(a.dims, b.dims)
                    if conflict:
                        self.emit(
                            e.lineno,
                            f"comparison in '{self.fn.name}' cannot broadcast {a.render()} with {b.render()}",
                        )
                    dims = d
                else:
                    ok = False
            if not ok:
                return AV((), "bool") if dims is None else AV(dims, "bool")
            return AV(dims, "bool")
        if isinstance(e, ast.BoolOp):
            for v in e.values:
                self.eval(v)
            return UNKNOWN
        if isinstance(e, ast.IfExp):
            self.eval(e.test)
            a, b = self.eval(e.body), self.eval(e.orelse)
            if isinstance(a, AV) and isinstance(b, AV):
                if a.dims == b.dims and a.dtype == b.dtype:
                    return a
                dims = a.dims if a.dims == b.dims else None
                return AV(dims, _merge_dtype(a.dtype, b.dtype) if a.dtype == b.dtype else None)
            return UNKNOWN
        if isinstance(e, (ast.Tuple, ast.List)):
            return _Tup([self.eval(x) for x in e.elts])
        if isinstance(e, ast.Starred):
            self.eval(e.value)
            return UNKNOWN
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return UNKNOWN  # own scope; targets unbound here
        if isinstance(e, ast.Lambda):
            return UNKNOWN
        if isinstance(e, ast.JoinedStr):
            return UNKNOWN
        if isinstance(e, ast.Dict):
            for v in e.values:
                if v is not None:
                    self.eval(v)
            return UNKNOWN
        return UNKNOWN

    def is_ns(self, e: ast.expr) -> bool:
        """Is ``e`` (the base of an attribute) an array namespace?"""
        if isinstance(e, ast.Name):
            return e.id in self.idx.ns_bases
        if isinstance(e, ast.Attribute):  # jnp.linalg style
            return self.is_ns(e.value)
        return False

    def eval_attribute(self, e: ast.Attribute):
        if self.is_ns(e.value):
            if e.attr in _NP_DTYPES:
                return _DtypeCtor(_NP_DTYPES[e.attr])
            if e.attr in ("inf", "nan", "pi", "e"):
                return AV((), None)
            return UNKNOWN  # namespace function referenced, not called
        v = self.eval(e.value)
        if isinstance(v, AV):
            if e.attr == "T":
                return AV(tuple(reversed(v.dims)) if v.dims is not None else None, v.dtype)
            if e.attr in ("real", "imag"):
                return v
        return UNKNOWN

    # -- indexing ------------------------------------------------------------

    def eval_subscript(self, e: ast.Subscript):
        recv = self.eval(e.value)
        # x.at[idx] rides through so .set/.add give x back (handled in call)
        if isinstance(e.value, ast.Attribute) and e.value.attr == "at":
            return UNKNOWN
        idx = e.slice
        elems = list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]
        if isinstance(recv, _Tup):
            if len(elems) == 1 and isinstance(elems[0], ast.Constant) and isinstance(elems[0].value, int):
                i = elems[0].value
                if -len(recv.items) <= i < len(recv.items):
                    v = recv.items[i]
                    return v if isinstance(v, (AV, _Tup)) else UNKNOWN
            return UNKNOWN
        if not isinstance(recv, AV) or recv.dims is None:
            for el in elems:
                if not isinstance(el, ast.Slice):
                    self.eval(el)
            return UNKNOWN
        if recv.dims == ():  # indexing a scalar: nonsense, but stay quiet
            return UNKNOWN
        # split around Ellipsis
        if any(isinstance(el, ast.Constant) and el.value is Ellipsis for el in elems):
            cut = next(i for i, el in enumerate(elems) if isinstance(el, ast.Constant) and el.value is Ellipsis)
            head, tail = elems[:cut], elems[cut + 1 :]
        else:
            head, tail = elems, []
        n_consumed = sum(1 for el in head + tail if not (isinstance(el, ast.Constant) and el.value is None))
        if n_consumed > len(recv.dims):
            self.emit(
                e.lineno,
                f"index with {n_consumed} axes into {recv.render()} in '{self.fn.name}'",
            )
            return UNKNOWN
        dims = list(recv.dims)
        out: list = []
        unknown = False

        def apply(el, dim_iter):
            nonlocal unknown
            if isinstance(el, ast.Constant) and el.value is None:
                out.append(1)
                return
            d = next(dim_iter)
            if isinstance(el, ast.Slice):
                out.append(self.slice_dim(el, d))
                return
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                if isinstance(d, int) and not (-d <= el.value < d):
                    self.emit(
                        e.lineno,
                        f"index {el.value} out of bounds for dim {d} of {recv.render()} in '{self.fn.name}'",
                    )
                return  # scalar index drops the dim
            v = self.eval(el)
            if isinstance(v, AV) and v.dims is not None and len(v.dims) == 1:
                out.append(v.dims[0])  # 1-D fancy index replaces the dim
                return
            if isinstance(v, AV) and v.dims == ():
                return  # scalar variable index drops the dim
            unknown = True

        if tail:
            # leading indices bind from the front, trailing from the back
            front = iter(dims[: len(dims)])
            n_tail = sum(1 for el in tail if not (isinstance(el, ast.Constant) and el.value is None))
            mid = dims[sum(1 for el in head if not (isinstance(el, ast.Constant) and el.value is None)) : len(dims) - n_tail]
            for el in head:
                apply(el, front)
            out.extend(mid)
            back = iter(dims[len(dims) - n_tail :])
            for el in tail:
                apply(el, back)
        else:
            it = iter(dims)
            for el in head:
                apply(el, it)
            out.extend(it)  # untouched trailing dims
        if unknown:
            return AV(None, recv.dtype)
        return AV(tuple(out), recv.dtype)

    def slice_dim(self, sl: ast.Slice, d):
        lo, hi, step = sl.lower, sl.upper, sl.step
        if lo is None and hi is None and step is None:
            return d  # full slice keeps the dim
        if lo is not None:
            self.eval(lo)
        if hi is not None:
            self.eval(hi)
        if (
            (lo is None or (isinstance(lo, ast.Constant) and lo.value == 0))
            and step is None
            and isinstance(hi, ast.Constant)
            and isinstance(hi.value, int)
            and hi.value >= 0
        ):
            if isinstance(d, int) and hi.value > d:
                return d
            return hi.value  # x[:k] — dim becomes k (assuming the dim covers it)
        return None

    # -- operators -----------------------------------------------------------

    def binop(self, a, op, b, lineno: int):
        if isinstance(op, ast.MatMult):
            return self.matmul(a, b, lineno)
        if not isinstance(a, AV) or not isinstance(b, AV):
            return UNKNOWN
        dims, conflict = _broadcast(a.dims, b.dims)
        if conflict:
            self.emit(
                lineno,
                f"cannot broadcast {a.render()} with {b.render()} in '{self.fn.name}'",
            )
        fa, fb = _family(a.dtype), _family(b.dtype)
        a_arr = a.dims is None or a.dims != ()
        b_arr = b.dims is None or b.dims != ()
        dtype: str | None
        if isinstance(op, _ARITH):
            if fa == "bool" and fb in ("int", "float") and a.known_shape and a.dims != ():
                self.emit(lineno, f"bool mask {a.render()} promoted into {b.dtype} arithmetic in '{self.fn.name}' — cast explicitly or use &/|")
            elif fb == "bool" and fa in ("int", "float") and b.known_shape and b.dims != ():
                self.emit(lineno, f"bool mask {b.render()} promoted into {a.dtype} arithmetic in '{self.fn.name}' — cast explicitly or use &/|")
            elif fa == "bool" and fb == "bool" and (a.dims != () or b.dims != ()):
                self.emit(lineno, f"arithmetic on bool masks in '{self.fn.name}' — use logical ops or cast explicitly")
            elif fa is not None and fb is not None and fa != fb and a_arr and b_arr and a.known_shape and b.known_shape:
                self.emit(lineno, f"implicit {a.dtype}/{b.dtype} promotion mixing int and float arrays in '{self.fn.name}' — cast explicitly")
            dtype = _merge_dtype(a.dtype, b.dtype) if fa == fb or fa is None or fb is None else None
            if isinstance(op, ast.Div) and fa == "int" and fb == "int":
                dtype = None  # true division promotes to float; width unknown
        elif isinstance(op, _BITWISE):
            if (fa == "bool") != (fb == "bool") and fa is not None and fb is not None:
                self.emit(lineno, f"bitwise op mixes {a.dtype} and {b.dtype} in '{self.fn.name}'")
                dtype = None
            else:
                dtype = _merge_dtype(a.dtype, b.dtype)
        elif isinstance(op, _SHIFT):
            dtype = a.dtype
        else:
            dtype = _merge_dtype(a.dtype, b.dtype)
        return AV(dims, dtype)

    def matmul(self, a, b, lineno: int):
        if not isinstance(a, AV) or not isinstance(b, AV) or a.dims is None or b.dims is None:
            return UNKNOWN
        da, db = a.dims, b.dims
        dtype = _merge_dtype(a.dtype, b.dtype)
        if len(da) == 2 and len(db) == 2:
            if _dim_conflict(da[1], db[0]):
                self.emit(
                    lineno,
                    f"matmul inner dims differ: {a.render()} @ {b.render()} in '{self.fn.name}' — transposed operand?",
                )
                return AV(None, dtype)  # suppress cascading findings
            return AV((da[0], db[1]), dtype)
        if len(da) == 1 and len(db) == 2:
            if _dim_conflict(da[0], db[0]):
                self.emit(lineno, f"matmul inner dims differ: {a.render()} @ {b.render()} in '{self.fn.name}'")
            return AV((db[1],), dtype)
        if len(da) == 2 and len(db) == 1:
            if _dim_conflict(da[1], db[0]):
                self.emit(lineno, f"matmul inner dims differ: {a.render()} @ {b.render()} in '{self.fn.name}'")
            return AV((da[0],), dtype)
        if len(da) == 1 and len(db) == 1:
            if _dim_conflict(da[0], db[0]):
                self.emit(lineno, f"matmul inner dims differ: {a.render()} @ {b.render()} in '{self.fn.name}'")
            return AV((), dtype)
        return UNKNOWN

    # -- calls ---------------------------------------------------------------

    def eval_call(self, e: ast.Call):
        f = e.func
        args = [self.eval(a) for a in e.args if not isinstance(a, ast.Starred)]
        if any(isinstance(a, ast.Starred) for a in e.args):
            for a in e.args:
                if isinstance(a, ast.Starred):
                    self.eval(a.value)
            args = None  # positional mapping unknowable
        kwargs = {}
        for kw in e.keywords:
            v = self.eval(kw.value)
            if kw.arg is not None:
                kwargs[kw.arg] = v

        if isinstance(f, ast.Attribute):
            # x.at[idx].set(v) and friends give x back
            if (
                f.attr in ("set", "add", "multiply", "divide", "min", "max", "get", "apply")
                and isinstance(f.value, ast.Subscript)
                and isinstance(f.value.value, ast.Attribute)
                and f.value.value.attr == "at"
            ):
                base = self.eval(f.value.value.value)
                idx = f.value.slice
                for el in list(idx.elts) if isinstance(idx, ast.Tuple) else [idx]:
                    if not isinstance(el, ast.Slice) and not (
                        isinstance(el, ast.Constant) and el.value in (None, Ellipsis)
                    ):
                        self.eval(el)
                return base if isinstance(base, AV) else UNKNOWN
            if self.is_ns(f.value):
                return self.ns_call(f.attr, e, args, kwargs)
            recv = self.eval(f.value)
            if isinstance(recv, AV):
                return self.method_call(recv, f.attr, e, args, kwargs)
            return UNKNOWN
        if isinstance(f, ast.Name):
            if f.id in self.dtype_ctors:
                return AV((), self.dtype_ctors[f.id])
            target = self.p.resolve(self.idx, f.id)
            if target is not None:
                return self.call_annotated(target, e, args, kwargs)
            if f.id in ("float", "int", "bool", "len", "abs", "round"):
                return AV((), {"float": "f64", "int": "i64", "bool": "bool"}.get(f.id))
            return UNKNOWN
        self.eval(f)
        return UNKNOWN

    def method_call(self, recv: AV, attr: str, e: ast.Call, args, kwargs):
        if attr == "astype":
            d = self.dtype_of_arg(e.args[0]) if e.args else None
            return AV(recv.dims, d)
        if attr in _REDUCTIONS or attr in _SCAN_REDUCTIONS:
            return self.reduction(attr, recv, e, axis_args=e.args, kwargs_nodes=e.keywords)
        if attr in ("copy", "block_until_ready", "conj"):
            return recv
        if attr == "item":
            return AV((), recv.dtype)
        if attr == "reshape":
            return self.reshape_result(recv, e.args)
        if attr == "transpose":
            if not e.args:
                return AV(tuple(reversed(recv.dims)) if recv.dims is not None else None, recv.dtype)
            return AV(None, recv.dtype)
        if attr in ("ravel", "flatten"):
            return AV((None,), recv.dtype)
        if attr == "tolist":
            return UNKNOWN
        return UNKNOWN

    def ns_call(self, name: str, e: ast.Call, args, kwargs):
        if name in _NP_DTYPES:
            return AV((), _NP_DTYPES[name])
        if args is None:
            return UNKNOWN
        a0 = args[0] if args else UNKNOWN

        if name in ("where", "select"):
            if len(args) == 3 and all(isinstance(a, AV) for a in args):
                c, x, y = args
                d1, conflict1 = _broadcast(c.dims, x.dims)
                d2, conflict2 = _broadcast(d1, y.dims)
                if conflict1 or conflict2:
                    self.emit(
                        e.lineno,
                        f"where() operands do not broadcast: {c.render()}, {x.render()}, {y.render()} in '{self.fn.name}'",
                    )
                return AV(d2, _merge_dtype(x.dtype, y.dtype))
            return UNKNOWN
        if name in _REDUCTIONS or name in _SCAN_REDUCTIONS:
            if isinstance(a0, AV):
                return self.reduction(name, a0, e, axis_args=e.args[1:], kwargs_nodes=e.keywords)
            return UNKNOWN
        if name in _ELEMENTWISE1:
            return a0 if isinstance(a0, AV) else UNKNOWN
        if name in _BOOL_OUT1:
            return AV(a0.dims, "bool") if isinstance(a0, AV) else UNKNOWN
        if name in _BINOP_FNS:
            if len(args) >= 2:
                return self.binop(args[0], ast.Add(), args[1], e.lineno)
            return UNKNOWN
        if name in _LOGICAL2:
            if len(args) >= 2 and isinstance(args[0], AV) and isinstance(args[1], AV):
                dims, conflict = _broadcast(args[0].dims, args[1].dims)
                if conflict:
                    self.emit(
                        e.lineno,
                        f"cannot broadcast {args[0].render()} with {args[1].render()} in '{self.fn.name}'",
                    )
                return AV(dims, "bool")
            return UNKNOWN
        if name in ("matmul", "dot"):
            if len(args) >= 2:
                return self.matmul(args[0], args[1], e.lineno)
            return UNKNOWN
        if name in ("zeros", "ones", "empty", "full"):
            dims = self.shape_of_arg(e.args[0]) if e.args else None
            dt_node = kwargs_node(e, "dtype") or (e.args[2] if name == "full" and len(e.args) > 2 else None)
            if dt_node is None and name != "full" and len(e.args) > 1:
                dt_node = e.args[1]
            return AV(dims, self.dtype_of_arg(dt_node) if dt_node is not None else None)
        if name in ("zeros_like", "ones_like", "empty_like", "full_like"):
            return a0 if isinstance(a0, AV) else UNKNOWN
        if name == "arange":
            d = None
            if len(e.args) == 1:
                d = self.dim_of_node(e.args[0])
            dt = kwargs_node(e, "dtype")
            return AV((d,), self.dtype_of_arg(dt) if dt is not None else None)
        if name in ("asarray", "array"):
            dt = kwargs_node(e, "dtype")
            dtype = self.dtype_of_arg(dt) if dt is not None else (a0.dtype if isinstance(a0, AV) else None)
            return AV(a0.dims, dtype) if isinstance(a0, AV) else AV(None, dtype)
        if name == "concatenate":
            return self.concat_result(e, args)
        if name == "stack":
            if e.args and isinstance(e.args[0], (ast.List, ast.Tuple)):
                parts = [self.eval(x) for x in e.args[0].elts]
                if parts and all(isinstance(p, AV) and p.dims is not None for p in parts):
                    base = parts[0].dims
                    return AV((len(parts),) + base, _merge_dtype_many([p.dtype for p in parts]))
            return UNKNOWN
        if name == "transpose":
            if isinstance(a0, AV) and a0.dims is not None and len(e.args) == 1:
                return AV(tuple(reversed(a0.dims)), a0.dtype)
            return UNKNOWN
        if name == "reshape":
            if isinstance(a0, AV):
                return self.reshape_result(a0, e.args[1:])
            return UNKNOWN
        if name == "broadcast_to":
            dims = self.shape_of_arg(e.args[1]) if len(e.args) > 1 else None
            return AV(dims, a0.dtype if isinstance(a0, AV) else None)
        if name == "expand_dims":
            return AV(None, a0.dtype) if isinstance(a0, AV) else UNKNOWN
        if name == "pad":
            if isinstance(a0, AV) and a0.dims is not None:
                return AV((None,) * len(a0.dims), a0.dtype)
            return UNKNOWN
        if name == "argsort":
            return AV(a0.dims, "i64") if isinstance(a0, AV) else UNKNOWN
        if name == "sort":
            return a0 if isinstance(a0, AV) else UNKNOWN
        if name == "dynamic_slice_in_dim":
            if isinstance(a0, AV) and a0.dims is not None:
                axis = 0
                ax_node = kwargs_node(e, "axis") or (e.args[3] if len(e.args) > 3 else None)
                if isinstance(ax_node, ast.Constant) and isinstance(ax_node.value, int):
                    axis = ax_node.value
                size = self.dim_of_node(e.args[2]) if len(e.args) > 2 else None
                dims = list(a0.dims)
                if -len(dims) <= axis < len(dims):
                    dims[axis] = size
                return AV(tuple(dims), a0.dtype)
            return UNKNOWN
        if name == "dynamic_update_slice_in_dim":
            return a0 if isinstance(a0, AV) else UNKNOWN
        if name == "axis_index":
            return AV((), "i32")
        if name in ("fromiter",):
            d = self.dim_of_node(e.args[2]) if len(e.args) > 2 else None
            dt = self.dtype_of_arg(e.args[1]) if len(e.args) > 1 else None
            return AV((d,), dt)
        # unmodeled namespace fn (while_loop, all_gather, associative_scan,
        # psum, einsum, ...) — args were already evaluated for findings
        return UNKNOWN

    def reduction(self, name: str, recv: AV, e: ast.Call, axis_args, kwargs_nodes):
        axis_node = None
        for kw in kwargs_nodes:
            if kw.arg == "axis":
                axis_node = kw.value
        if axis_node is None and axis_args:
            axis_node = axis_args[0]
        keepdims = any(
            kw.arg == "keepdims" and isinstance(kw.value, ast.Constant) and kw.value.value
            for kw in kwargs_nodes
        )
        rank = len(recv.dims) if recv.dims is not None else None
        axes: list[int] | None = None
        if axis_node is None:
            axes = None if name in _SCAN_REDUCTIONS else "ALL"  # type: ignore[assignment]
        elif isinstance(axis_node, ast.Constant) and isinstance(axis_node.value, int):
            axes = [axis_node.value]
        elif isinstance(axis_node, ast.UnaryOp) and isinstance(axis_node.op, ast.USub) and isinstance(
            axis_node.operand, ast.Constant
        ):
            axes = [-axis_node.operand.value]
        elif isinstance(axis_node, (ast.Tuple, ast.List)) and all(
            isinstance(x, ast.Constant) and isinstance(x.value, int) for x in axis_node.elts
        ):
            axes = [x.value for x in axis_node.elts]
        else:
            self.eval(axis_node)
            axes = None if name in _SCAN_REDUCTIONS else "SOME"  # type: ignore[assignment]

        if isinstance(axes, list) and rank is not None:
            for ax in axes:
                if not (-rank <= ax < rank):
                    self.emit(
                        e.lineno,
                        f"{name}(axis={ax}) out of range for {recv.render()} (rank {rank}) in '{self.fn.name}'",
                    )
                    return AV(None, recv.dtype)  # suppress cascading findings
        if name in ("any", "all"):
            dtype = "bool"
        elif name in ("argmax", "argmin"):
            dtype = "i64"
        elif name in ("sum", "prod", "cumsum", "cumprod", "count_nonzero"):
            dtype = "i64" if recv.dtype == "bool" else ("i64" if name == "count_nonzero" else recv.dtype)
        elif name in ("mean", "std", "var"):
            dtype = recv.dtype if _family(recv.dtype) == "float" else None
        else:
            dtype = recv.dtype
        if name in _SCAN_REDUCTIONS:
            return AV(recv.dims, dtype)
        if rank is None:
            return AV(None, dtype)
        if axes == "ALL":
            return AV((1,) * rank if keepdims else (), dtype)
        if axes == "SOME" or axes is None:
            return AV(None, dtype)
        dims = list(recv.dims)
        for ax in sorted({ax % rank for ax in axes if -rank <= ax < rank}, reverse=True):
            if keepdims:
                dims[ax] = 1
            else:
                del dims[ax]
        return AV(tuple(dims), dtype)

    def concat_result(self, e: ast.Call, args):
        if not e.args or not isinstance(e.args[0], (ast.List, ast.Tuple)):
            return UNKNOWN
        parts = [self.eval(x) for x in e.args[0].elts]
        if not parts or not all(isinstance(p, AV) and p.dims is not None for p in parts):
            return UNKNOWN
        axis = 0
        ax_node = kwargs_node(e, "axis") or (e.args[1] if len(e.args) > 1 else None)
        if isinstance(ax_node, ast.Constant) and isinstance(ax_node.value, int):
            axis = ax_node.value
        elif isinstance(ax_node, ast.UnaryOp) and isinstance(ax_node.op, ast.USub) and isinstance(
            ax_node.operand, ast.Constant
        ):
            axis = -ax_node.operand.value
        rank = len(parts[0].dims)
        if any(len(p.dims) != rank for p in parts) or not (-rank <= axis < rank):
            return UNKNOWN
        axis %= rank
        dims = list(parts[0].dims)
        for p in parts[1:]:
            for i in range(rank):
                if i == axis:
                    continue
                if _dim_conflict(dims[i], p.dims[i]):
                    self.emit(
                        e.lineno,
                        f"concatenate non-axis dims differ: {parts[0].render()} vs {p.render()} in '{self.fn.name}'",
                    )
                elif dims[i] == 1:
                    dims[i] = p.dims[i]
        if all(isinstance(p.dims[axis], int) for p in parts):
            dims[axis] = sum(p.dims[axis] for p in parts)
        else:
            dims[axis] = None
        return AV(tuple(dims), _merge_dtype_many([p.dtype for p in parts]))

    def reshape_result(self, recv: AV, shape_args):
        if len(shape_args) == 1 and isinstance(shape_args[0], (ast.Tuple, ast.List)):
            elts = shape_args[0].elts
        else:
            elts = shape_args
        dims = []
        for el in elts:
            d = self.dim_of_node(el)
            if isinstance(el, ast.Constant) and el.value == -1:
                d = None
            dims.append(d)
        return AV(tuple(dims) if dims else None, recv.dtype)

    def shape_of_arg(self, node) -> tuple | None:
        """A shape literal: ``(p_pad, t_pad)`` / ``(n, 2)`` / a bare int."""
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.dim_of_node(el) for el in node.elts)
        d = self.dim_of_node(node)
        return (d,) if d is not None else None

    def dim_of_node(self, node):
        """A dim expression -> symbolic dim: literal int, or the NAME of a
        scalar variable (scalar params become symbols, tying allocation
        shapes to the contract)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        if isinstance(node, ast.Name):
            v = self.env.get(node.id)
            if isinstance(v, AV) and v.dims not in ((), None):
                return None  # a tensor, not a scalar dim
            return node.id
        self.eval(node)
        return None

    def dtype_of_arg(self, node) -> str | None:
        if node is None:
            return None
        v = self.eval(node)
        if isinstance(v, _DtypeCtor):
            return v.dtype
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return _NP_DTYPES.get(node.value) or _DTYPE_TOKENS.get(node.value)
        return None

    # -- interprocedural -----------------------------------------------------

    def call_annotated(self, target, e: ast.Call, args, kwargs):
        callee_fn, contract = target
        if args is None:
            return UNKNOWN
        params = [
            a.arg for a in (callee_fn.args.posonlyargs + callee_fn.args.args)
        ]
        if params and params[0] == "self":
            params = params[1:]
        by_name: dict[str, object] = {}
        for name, v in zip(params, args):
            by_name[name] = v
        by_name.update(kwargs)
        specs = dict(contract.params)
        binding: dict[str, object] = {}
        for name, got in by_name.items():
            spec = specs.get(name)
            if spec is None or not isinstance(got, AV):
                continue
            if spec.dims is None or got.dims is None:
                continue
            if len(spec.dims) != len(got.dims):
                if got.dims == ():
                    continue  # a scalar fed to a tensor slot: runtime broadcast
                self.emit(
                    e.lineno,
                    f"'{callee_fn.name}' arg '{name}' declares {spec.render()} but got rank-{len(got.dims)} {got.render()}",
                )
                continue
            for sd, gd in zip(spec.dims, got.dims):
                if isinstance(sd, int):
                    if isinstance(gd, int) and sd != gd:
                        self.emit(
                            e.lineno,
                            f"'{callee_fn.name}' arg '{name}' declares {spec.render()} but got {got.render()}",
                        )
                        break
                    continue
                if sd is None:
                    continue
                prev = binding.get(sd, "__unset__")
                if prev == "__unset__" or prev is None:
                    binding[sd] = gd
                elif gd is not None and _dim_conflict(prev, gd):
                    self.emit(
                        e.lineno,
                        f"'{callee_fn.name}' arg '{name}': dim {sd} was {prev} from an earlier arg but is {gd} here — transposed operand?",
                    )
                    binding[sd] = None
                    break  # one finding per mismatched argument
            gf, sf_ = _family(got.dtype), _family(spec.dtype)
            if gf is not None and sf_ is not None and gf != sf_:
                self.emit(
                    e.lineno,
                    f"'{callee_fn.name}' arg '{name}' declares dtype {spec.dtype} but got {got.dtype}",
                )

        def subst(spec):
            if spec is None or spec.dims is None:
                return UNKNOWN if spec is None else AV(None, spec.dtype)
            dims = tuple(
                d if isinstance(d, int) else binding.get(d) if isinstance(d, str) else None
                for d in spec.dims
            )
            return AV(dims, spec.dtype)

        ret = contract.ret
        if isinstance(ret, _Tup):
            return _Tup([subst(s) for s in ret.items])
        return subst(ret)


def kwargs_node(e: ast.Call, name: str):
    for kw in e.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _merge_dtype_many(dts: list) -> str | None:
    out = dts[0] if dts else None
    for d in dts[1:]:
        out = _merge_dtype(out, d)
    return out


# -- pass driver -------------------------------------------------------------


class _PassCtx:
    """Cross-module resolution: annotated top-level function name ->
    (FunctionDef, Contract), same-module first, then from-imports."""

    def __init__(self, files: list[SourceFile]):
        self.indices: dict[str, _ModIndex] = {}
        self.contracts: dict[str, dict[ast.FunctionDef, tuple[Contract | None, str | None]]] = {}
        self.by_name: dict[str, tuple[ast.FunctionDef, Contract]] = {}
        self.local: dict[str, dict[str, tuple[ast.FunctionDef, Contract]]] = {}
        for f in files:
            idx = _ModIndex(f)
            self.indices[f.rel] = idx
            cons = _collect_contracts(f)
            self.contracts[f.rel] = cons
            loc: dict[str, tuple[ast.FunctionDef, Contract]] = {}
            for fn, (contract, err) in cons.items():
                if contract is not None:
                    loc[fn.name] = (fn, contract)
            self.local[f.rel] = loc
            self.by_name.update(loc)
        self._current_rel: str | None = None

    def resolve(self, idx: _ModIndex, name: str):
        loc = self.local.get(idx.sf.rel, {})
        if name in loc:
            return loc[name]
        if name in idx.from_imports and name in self.by_name:
            return self.by_name[name]
        return None


def run(ctx: Context) -> list[Finding]:
    files = [f for f in ctx.parsed() if "# shape:" in f.text]
    if not files:
        return []
    p = _PassCtx(files)
    findings: list[Finding] = []
    for f in files:
        idx = p.indices[f.rel]
        for fn, (contract, err) in sorted(p.contracts[f.rel].items(), key=lambda kv: kv[0].lineno):
            if err is not None:
                findings.append(Finding("SHPE", f.rel, fn.lineno, err))
                continue
            assert contract is not None
            findings.extend(_FnChecker(p, idx, fn, contract).check())
    return findings
