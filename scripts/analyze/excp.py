"""EXCP — the failure-class taxonomy must stay CLOSED.

``Scheduler._requeue_reason_class`` (runtime/controller.py) is the single
source of requeue failure classes; every class it can produce must have a
``BackoffQueue`` policy (``DEFAULT_POLICIES`` in runtime/resilience.py), a
row in the README Resilience failure-class table, and an entry on the
``scheduler_requeues_by_reason_total{reason=...}`` metric catalogue row —
and every policy must be REACHABLE (a key the controller can never produce
is dead config that hides a renamed class).  PR 4 wired the taxonomy
through three layers by hand; this rule fails the build on any gap in
either direction, so adding (or renaming) a failure class without teaching
the backoff queue and the docs is impossible.

Label extraction is AST-based, not regex: constants returned by the
classifier, plus the membership tuples guarding ``return <var>`` (the
``if head in ("api-error", "network-error"): return head`` form).
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding

CODES = {
    "EXCP": "a requeue failure class without a backoff policy / metric row / README row (or a policy no class produces) — the taxonomy must stay closed",
}

# Needs controller.py AND resilience.py AND the README together — a partial
# (--changed-only) context would flag one side as missing when it is merely
# unloaded, so the driver only runs this pass on full-context runs.
FILE_SCOPED = False

_CONTROLLER = "tpu_scheduler/runtime/controller.py"
_RESILIENCE = "tpu_scheduler/runtime/resilience.py"
_METRIC = "scheduler_requeues_by_reason_total"


def _classifier_labels(tree: ast.Module) -> tuple[set[str], int] | None:
    """Labels ``_requeue_reason_class`` can produce, + its line (None when
    the classifier is absent from the file)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_requeue_reason_class":
            labels: set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Return):
                    if isinstance(sub.value, ast.Constant) and isinstance(sub.value.value, str):
                        labels.add(sub.value.value)
                elif isinstance(sub, ast.If):
                    # `if <var> in ("a", "b"): return <var>` — the tuple IS
                    # the label set for that branch.
                    t = sub.test
                    returns_var = any(
                        isinstance(s, ast.Return) and isinstance(s.value, ast.Name) for s in sub.body
                    )
                    if (
                        returns_var
                        and isinstance(t, ast.Compare)
                        and len(t.ops) == 1
                        and isinstance(t.ops[0], ast.In)
                        and isinstance(t.comparators[0], (ast.Tuple, ast.List))
                    ):
                        ret_names = {
                            s.value.id
                            for s in sub.body
                            if isinstance(s, ast.Return) and isinstance(s.value, ast.Name)
                        }
                        if isinstance(t.left, ast.Name) and t.left.id in ret_names:
                            labels.update(
                                e.value
                                for e in t.comparators[0].elts
                                if isinstance(e, ast.Constant) and isinstance(e.value, str)
                            )
            return labels, node.lineno
    return None


def _policy_classes(tree: ast.Module) -> tuple[set[str], int] | None:
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "DEFAULT_POLICIES" and isinstance(node.value, ast.Dict):
                    keys = {
                        k.value for k in node.value.keys if isinstance(k, ast.Constant) and isinstance(k.value, str)
                    }
                    return keys, node.lineno
    return None


def run(ctx: Context) -> list[Finding]:
    controller = resilience = None
    for f in ctx.parsed():
        if f.rel == _CONTROLLER:
            controller = f
        elif f.rel == _RESILIENCE:
            resilience = f
    if controller is None or resilience is None:
        return []  # partial context: closure is unjudgeable, stay silent
    produced = _classifier_labels(controller.tree)
    policies = _policy_classes(resilience.tree)
    if produced is None or policies is None:
        out = []
        if produced is None:
            out.append(Finding("EXCP", _CONTROLLER, 1, "Scheduler._requeue_reason_class not found — the EXCP taxonomy anchor moved"))
        if policies is None:
            out.append(Finding("EXCP", _RESILIENCE, 1, "DEFAULT_POLICIES not found — the EXCP backoff-policy anchor moved"))
        return out
    labels, cls_line = produced
    keys, pol_line = policies

    findings: list[Finding] = []
    for label in sorted(labels - keys):
        findings.append(
            Finding(
                "EXCP",
                _RESILIENCE,
                pol_line,
                f"requeue class '{label}' is produced by Scheduler._requeue_reason_class but has no BackoffQueue policy in DEFAULT_POLICIES",
            )
        )
    for key in sorted(keys - labels):
        findings.append(
            Finding(
                "EXCP",
                _CONTROLLER,
                cls_line,
                f"backoff policy class '{key}' is never produced by Scheduler._requeue_reason_class — dead policy or renamed class",
            )
        )

    # README: the metric catalogue row must enumerate every class, and the
    # Resilience failure-class table must carry a `| \`class\` |` row.
    metric_rows = " ".join(line for line in ctx.readme.splitlines() if _METRIC in line)
    for label in sorted(labels | keys):
        if f"`{label}`" not in metric_rows and label not in metric_rows:
            findings.append(
                Finding(
                    "EXCP",
                    "README.md",
                    1,
                    f"requeue class '{label}' is missing from the README {_METRIC} metric catalogue row",
                )
            )
        if not re.search(rf"^\|\s*`?{re.escape(label)}`?\s*\|", ctx.readme, re.MULTILINE):
            findings.append(
                Finding(
                    "EXCP",
                    "README.md",
                    1,
                    f"requeue class '{label}' has no row in the README Resilience failure-class table",
                )
            )
    return findings
