"""DTRM — simulator determinism: ``tpu_scheduler/sim/`` may only consume
virtual time and the seeded rng.

The record/replay contract (sim/trace.py) is byte-identity: the same
scenario + seed must produce the same fingerprint on every run of every
machine.  One wall-clock read or global-rng draw anywhere in sim/ breaks
that silently — the replay float-rounding incident took a day to localize
because nothing pointed at the source.  Forbidden in sim/ modules:

  • ``time.time`` / ``time.monotonic`` / ``time.sleep`` / ``time.perf_counter``
    (and their ``_ns`` twins) — the VirtualClock is the only time source
  • module-level ``random.*`` calls — the process-global rng is unseeded
    shared state; ``random.Random(seed)`` instances are the sanctioned form
  • ``datetime.now`` / ``datetime.utcnow`` / ``datetime.today`` — wall clock
  • ``os.urandom`` / ``uuid.uuid4`` — entropy
  • iterating a ``set`` literal / ``set(...)`` call (for-loops and
    comprehensions) — set order is hash-seed-dependent, and sim iteration
    feeds trace lines and scorecard JSON
"""

from __future__ import annotations

import ast

from .core import Context, Finding, SourceFile

CODES = {
    "DTRM": "wall clock, global rng, entropy, or set-order iteration in sim/ — breaks record/replay byte-identity",
}

# Strictly per-file — safe under the driver's --changed-only fast path.
FILE_SCOPED = True

_TIME_ATTRS = ("time", "monotonic", "sleep", "perf_counter", "time_ns", "monotonic_ns", "perf_counter_ns")
_DATETIME_ATTRS = ("now", "utcnow", "today")


def _check_file(f: SourceFile, findings: list[Finding]) -> None:
    tree = f.tree
    assert tree is not None
    time_aliases: set[str] = set()
    random_aliases: set[str] = set()
    from_time: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                if a.name == "time":
                    time_aliases.add(bound)
                elif a.name == "random":
                    random_aliases.add(bound)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                from_time.update((a.asname or a.name) for a in node.names if a.name in _TIME_ATTRS)
            elif node.module == "random":
                from_time.update(
                    (a.asname or a.name) for a in node.names if a.name != "Random"
                )  # bare draws from the global rng

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
                base, attr = fn.value.id, fn.attr
                if base in time_aliases and attr in _TIME_ATTRS:
                    findings.append(
                        Finding("DTRM", f.rel, node.lineno, f"time.{attr}() in sim/ — use the VirtualClock")
                    )
                elif base in random_aliases and attr != "Random":
                    findings.append(
                        Finding(
                            "DTRM",
                            f.rel,
                            node.lineno,
                            f"module-level random.{attr}() in sim/ — draw from a seeded random.Random instance",
                        )
                    )
                elif attr in _DATETIME_ATTRS and base in ("datetime", "date"):
                    findings.append(
                        Finding("DTRM", f.rel, node.lineno, f"{base}.{attr}() wall clock in sim/ — use the VirtualClock")
                    )
                elif base == "os" and attr == "urandom":
                    findings.append(
                        Finding("DTRM", f.rel, node.lineno, "os.urandom() entropy in sim/ — derive from the scenario seed")
                    )
                elif base == "uuid" and attr == "uuid4":
                    findings.append(
                        Finding("DTRM", f.rel, node.lineno, "uuid.uuid4() entropy in sim/ — derive from the scenario seed")
                    )
            elif isinstance(fn, ast.Name) and fn.id in from_time:
                findings.append(
                    Finding(
                        "DTRM",
                        f.rel,
                        node.lineno,
                        f"{fn.id}() (from time/random import) in sim/ — use the VirtualClock / a seeded Random",
                    )
                )
        iters: list[ast.expr] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iters.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            iters.extend(g.iter for g in node.generators)
        for it in iters:
            if isinstance(it, ast.Set) or (
                isinstance(it, ast.Call) and isinstance(it.func, ast.Name) and it.func.id == "set"
            ):
                findings.append(
                    Finding(
                        "DTRM",
                        f.rel,
                        it.lineno,
                        "iteration over a set in sim/ — order is hash-seed-dependent; sort it first",
                    )
                )


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for f in ctx.parsed():
        if f.in_package("tpu_scheduler", "sim"):
            _check_file(f, findings)
    return findings
