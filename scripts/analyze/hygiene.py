"""Hygiene passes ported from the monolithic ``scripts/lint.py``: the error
classes a round-2 regression shipped with (stale imports, phantom exports)
plus basic mechanical hygiene, on the stdlib so the gate runs in the build
image (which carries no installable linter).

Scope: the WHOLE analyzed tree — ``tpu_scheduler/``, ``tests/``,
``scripts/``, ``bench.py``, ``__graft_entry__.py`` (every file the driver
loads; there is no package filter here, and tests/test_analyze.py pins that
a violation seeded under tests/ or scripts/ is flagged)."""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, SourceFile, module_all, top_level_defs

CODES = {
    "E999": "syntax errors (ast.parse) — nothing else is checkable past one",
    "W291": "trailing whitespace — diff noise that masks real changes",
    "W191": "tabs in indentation — one indentation currency repo-wide",
    "E711": "comparison to None with ==/!= — use is / is not",
    "E712": "comparison to True/False with ==/!= — use the value or is",
    "E722": "bare except: — swallows KeyboardInterrupt/SystemExit and hides real faults; name the exception",
    "E741": "ambiguous single-char binding (l/O/I) — unreadable in most fonts, a classic transcription bug",
    "B006": "mutable default argument — shared across calls, a classic aliasing bug",
    "F841": "local assigned once and never read — dead stores hide logic errors",
    "F401": "imported name never used in the module — stale-import rot",
    "F822": "__all__ names a symbol the module does not define — phantom export",
}

# Per-file rules only — safe under the driver's --changed-only fast path.
FILE_SCOPED = True

_AMBIGUOUS = ("l", "O", "I")


class _FunctionScopeChecks:
    """Per-function rules: F841 unused locals, B006 mutable defaults."""

    def __init__(self, relpath: str, findings: list[Finding]):
        self.relpath = relpath
        self.findings = findings
        self._reads_cache: dict[int, set[str]] = {}

    def _subtree_reads(self, root) -> set:
        """Every name READ in the subtree (Name Loads plus AugAssign
        targets, which mutate in place).  Memoized at nested-scope roots so
        an enclosing function reuses its inner functions' sets instead of
        re-walking them — the walk stays linear in the module, not
        quadratic in nesting depth."""
        cached = self._reads_cache.get(id(root))
        if cached is not None:
            return cached
        reads: set[str] = set()
        stack = [root]
        while stack:
            n = stack.pop()
            if n is not root and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                reads |= self._subtree_reads(n)
                continue
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    reads.add(n.id)
                continue  # Name nodes are leaves bar the ctx
            if isinstance(n, ast.AugAssign) and isinstance(n.target, ast.Name):
                reads.add(n.target.id)
            stack.extend(ast.iter_child_nodes(n))
        self._reads_cache[id(root)] = reads
        return reads

    def _check_function(self, node):
        # B006 — mutable literals/constructors as parameter defaults.
        for default in list(node.args.defaults) + [d for d in node.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                self.findings.append(Finding("B006", self.relpath, default.lineno, "mutable default argument"))
        # F841 — plain-name single assignments never read in the function.
        # STORES are collected from this function's OWN scope only (nested
        # function bodies get their own visit — walking them here would
        # double-report their dead stores against the outer scope); READS
        # come from the full walk so a closure's use of an outer local still
        # counts (conservative: an inner local shadowing an outer name can
        # mask an outer dead store — false negatives over false positives).
        def own_scope(n):
            for child in ast.iter_child_nodes(n):
                # Nested functions/lambdas AND class bodies are their own
                # scopes — a class attribute is not a function local (it is
                # read via ast.Attribute, which never registers as a Name
                # Load, so walking it would hard-fail valid code).
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                    continue
                yield child
                yield from own_scope(child)

        assigned: dict[str, int] = {}
        # READS (including AugAssign in-place mutation — the
        # ledger-accumulator pattern is a use, not a dead store) come from
        # the full subtree so a closure's use of an outer local counts.
        read: set[str] = self._subtree_reads(node)
        exempt: set[str] = set()
        for sub in own_scope(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                assigned.setdefault(sub.id, sub.lineno)
            # global/nonlocal writes are module/outer-scope effects, and
            # loop induction variables are iteration plumbing (ruff would
            # file them under B007) — neither is an unused LOCAL.
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                exempt.update(sub.names)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                exempt.update(n.id for n in ast.walk(sub.target) if isinstance(n, ast.Name))
            elif isinstance(sub, ast.comprehension):
                exempt.update(n.id for n in ast.walk(sub.target) if isinstance(n, ast.Name))
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                # `with ... as x:` targets are context handles pyflakes/ruff
                # never file under F841 (e.g. pytest.raises(...) as exc).
                for item in sub.items:
                    if item.optional_vars is not None:
                        exempt.update(n.id for n in ast.walk(item.optional_vars) if isinstance(n, ast.Name))
            elif isinstance(sub, ast.Assign):
                # Tuple-unpack targets document structure — exempt them.
                for t in sub.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        exempt.update(n.id for n in ast.walk(t) if isinstance(n, ast.Name))
        args = {a.arg for a in node.args.args + node.args.kwonlyargs + node.args.posonlyargs}
        for name, lineno in sorted(assigned.items(), key=lambda kv: kv[1]):
            if name in read or name in exempt or name in args or name.startswith("_"):
                continue
            if name in ("self", "cls"):
                continue
            self.findings.append(Finding("F841", self.relpath, lineno, f"local variable '{name}' assigned but never used"))


def _check_module(f: SourceFile, findings: list[Finding]) -> None:
    tree = f.tree
    assert tree is not None
    rel = f.rel
    imports: dict[str, int] = {}  # bound name -> lineno
    used: set[str] = set()
    scopes = _FunctionScopeChecks(rel, findings)
    # ONE walk of the module drives every per-node rule — E722/E741
    # (bare except, ambiguous bindings), E711/E712 (None/bool compares,
    # both sides so Yoda comparisons are caught too), import collection
    # for F401, and the per-function scope checks (B006/F841) — these
    # used to be four separate full traversals of the same tree.
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                used.add(node.id)
            elif isinstance(node.ctx, ast.Store) and node.id in _AMBIGUOUS:
                findings.append(Finding("E741", rel, node.lineno, f"ambiguous variable name '{node.id}'"))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes._check_function(node)
        elif isinstance(node, ast.Compare):
            # Operand i of op i is left for i == 0, else comparators[i-1].
            operands = [node.left] + list(node.comparators)
            for i, op in enumerate(node.ops):
                if not isinstance(op, (ast.Eq, ast.NotEq)):
                    continue
                for side in (operands[i], operands[i + 1]):
                    if not isinstance(side, ast.Constant):
                        continue
                    if side.value is None:
                        findings.append(Finding("E711", rel, node.lineno, "comparison to None (use 'is'/'is not')"))
                    elif side.value is True or side.value is False:
                        findings.append(
                            Finding("E712", rel, node.lineno, f"comparison to {side.value} (use the value or 'is')")
                        )
        elif isinstance(node, ast.arg):
            if node.arg in _AMBIGUOUS:
                findings.append(Finding("E741", rel, node.lineno, f"ambiguous argument name '{node.arg}'"))
        elif isinstance(node, ast.ExceptHandler):
            if node.type is None:
                findings.append(Finding("E722", rel, node.lineno, "bare 'except:' — name the exception"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            # future imports act by existing, never by reference
            if node.module != "__future__":
                for a in node.names:
                    if a.name != "*":
                        imports[a.asname or a.name] = node.lineno
    exported = set(module_all(tree))
    # Names referenced in string annotations / docstring doctests are out
    # of scope; __init__ re-exports are legitimate when listed in __all__.
    is_init = f.path.name == "__init__.py"
    for name, lineno in imports.items():
        if name in used or name == "_":
            continue
        if is_init or name in exported:
            continue
        # A conservative text check catches usage forms the AST walk does
        # not model (e.g. inside f-string format specs).
        if len(re.findall(rf"\b{re.escape(name)}\b", f.text)) > 1:
            continue
        findings.append(Finding("F401", f.rel, lineno, f"'{name}' imported but unused"))
    defined = top_level_defs(tree)
    for name in exported:
        if name not in defined:
            findings.append(Finding("F822", f.rel, 1, f"undefined name '{name}' in __all__"))


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for f in ctx.files:
        for i, line in enumerate(f.lines, 1):
            if line != line.rstrip():
                findings.append(Finding("W291", f.rel, i, "trailing whitespace"))
            if line.startswith("\t"):
                findings.append(Finding("W191", f.rel, i, "tab in indentation"))
        if f.tree is not None:
            _check_module(f, findings)
    return findings
