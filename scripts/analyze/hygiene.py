"""Hygiene passes ported from the monolithic ``scripts/lint.py``: the error
classes a round-2 regression shipped with (stale imports, phantom exports)
plus basic mechanical hygiene, on the stdlib so the gate runs in the build
image (which carries no installable linter).

Scope: the WHOLE analyzed tree — ``tpu_scheduler/``, ``tests/``,
``scripts/``, ``bench.py``, ``__graft_entry__.py`` (every file the driver
loads; there is no package filter here, and tests/test_analyze.py pins that
a violation seeded under tests/ or scripts/ is flagged)."""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, SourceFile, module_all, top_level_defs

CODES = {
    "E999": "syntax errors (ast.parse) — nothing else is checkable past one",
    "W291": "trailing whitespace — diff noise that masks real changes",
    "W191": "tabs in indentation — one indentation currency repo-wide",
    "E711": "comparison to None with ==/!= — use is / is not",
    "E712": "comparison to True/False with ==/!= — use the value or is",
    "E722": "bare except: — swallows KeyboardInterrupt/SystemExit and hides real faults; name the exception",
    "E741": "ambiguous single-char binding (l/O/I) — unreadable in most fonts, a classic transcription bug",
    "B006": "mutable default argument — shared across calls, a classic aliasing bug",
    "F841": "local assigned once and never read — dead stores hide logic errors",
    "F401": "imported name never used in the module — stale-import rot",
    "F822": "__all__ names a symbol the module does not define — phantom export",
}

# Per-file rules only — safe under the driver's --changed-only fast path.
FILE_SCOPED = True

_AMBIGUOUS = ("l", "O", "I")


class _FnScope:
    """One function's F841 state, filled during the single module walk.

    STORES are collected from the function's OWN scope only (nested
    function/lambda/class bodies get their own record — counting them here
    would double-report their dead stores against the outer scope); READS
    come from the full subtree so a closure's use of an outer local still
    counts (conservative: an inner local shadowing an outer name can mask
    an outer dead store — false negatives over false positives).
    AugAssign targets count as READS — the ledger-accumulator pattern is a
    use, not a dead store."""

    __slots__ = ("relpath", "assigned", "reads", "exempt", "args")

    def __init__(self, relpath: str, node) -> None:
        self.relpath = relpath
        self.assigned: dict[str, int] = {}
        self.reads: set[str] = set()
        self.exempt: set[str] = set()
        self.args = {a.arg for a in node.args.args + node.args.kwonlyargs + node.args.posonlyargs}

    def finalize(self, findings: list[Finding]) -> None:
        for name, lineno in sorted(self.assigned.items(), key=lambda kv: kv[1]):
            if name in self.reads or name in self.exempt or name in self.args or name.startswith("_"):
                continue
            if name in ("self", "cls"):
                continue
            findings.append(Finding("F841", self.relpath, lineno, f"local variable '{name}' assigned but never used"))


def _check_module(f: SourceFile, findings: list[Finding]) -> None:
    tree = f.tree
    assert tree is not None
    rel = f.rel
    imports: dict[str, int] = {}  # bound name -> lineno
    used: set[str] = set()
    # ONE walk of the module drives every rule — E722/E741 (bare except,
    # ambiguous bindings), E711/E712 (None/bool compares, both sides so
    # Yoda comparisons are caught too), import collection for F401, AND
    # the per-function scope state (B006/F841).  The F841 reads/stores
    # used to be two more full traversals (subtree reads per function,
    # own-scope stores per function); here each node is visited exactly
    # once carrying its enclosing-function context: ``fscopes`` is the
    # stack of _FnScope records whose subtree contains the node (a Name
    # Load feeds every one of them — that is exactly the old full-subtree
    # reads semantics), and ``own`` says whether plain stores at this node
    # belong to ``fscopes[-1]``'s own scope (False under a lambda/class
    # barrier — a class attribute is not a function local — and at module
    # level).
    records: list[_FnScope] = []
    stack: list = [(node, (), False) for node in ast.iter_child_nodes(tree)]
    while stack:
        node, fscopes, own = stack.pop()
        t = type(node)
        if t is ast.Name:
            if isinstance(node.ctx, ast.Load):
                used.add(node.id)
                for r in fscopes:
                    r.reads.add(node.id)
            elif isinstance(node.ctx, ast.Store):
                if node.id in _AMBIGUOUS:
                    findings.append(Finding("E741", rel, node.lineno, f"ambiguous variable name '{node.id}'"))
                if own:
                    r = fscopes[-1]
                    # Earliest store wins (stack order is not document
                    # order, so keep the min lineno explicitly).
                    prev = r.assigned.get(node.id)
                    if prev is None or node.lineno < prev:
                        r.assigned[node.id] = node.lineno
            continue  # Name nodes are leaves bar the ctx
        if t is ast.Constant:
            continue
        if t in (ast.FunctionDef, ast.AsyncFunctionDef):
            # B006 — mutable literals/constructors as parameter defaults.
            for default in list(node.args.defaults) + [d for d in node.args.kw_defaults if d is not None]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set")
                ):
                    findings.append(Finding("B006", rel, default.lineno, "mutable default argument"))
            r = _FnScope(rel, node)
            records.append(r)
            inner = fscopes + (r,)
            stack.extend((child, inner, True) for child in ast.iter_child_nodes(node))
            continue
        if t in (ast.Lambda, ast.ClassDef):
            # Scope barrier: reads still reach the enclosing functions (a
            # closure use counts), but stores are no longer their locals.
            stack.extend((child, fscopes, False) for child in ast.iter_child_nodes(node))
            continue
        if t is ast.Compare:
            # Operand i of op i is left for i == 0, else comparators[i-1].
            operands = [node.left] + list(node.comparators)
            for i, op in enumerate(node.ops):
                if not isinstance(op, (ast.Eq, ast.NotEq)):
                    continue
                for side in (operands[i], operands[i + 1]):
                    if not isinstance(side, ast.Constant):
                        continue
                    if side.value is None:
                        findings.append(Finding("E711", rel, node.lineno, "comparison to None (use 'is'/'is not')"))
                    elif side.value is True or side.value is False:
                        findings.append(
                            Finding("E712", rel, node.lineno, f"comparison to {side.value} (use the value or 'is')")
                        )
        elif t is ast.arg:
            if node.arg in _AMBIGUOUS:
                findings.append(Finding("E741", rel, node.lineno, f"ambiguous argument name '{node.arg}'"))
        elif t is ast.ExceptHandler:
            if node.type is None:
                findings.append(Finding("E722", rel, node.lineno, "bare 'except:' — name the exception"))
        elif t is ast.Import:
            for a in node.names:
                imports[a.asname or a.name.split(".")[0]] = node.lineno
        elif t is ast.ImportFrom:
            # future imports act by existing, never by reference
            if node.module != "__future__":
                for a in node.names:
                    if a.name != "*":
                        imports[a.asname or a.name] = node.lineno
        elif t is ast.AugAssign:
            # In-place mutation is a USE of the target, not a dead store.
            if isinstance(node.target, ast.Name):
                for r in fscopes:
                    r.reads.add(node.target.id)
        elif own:
            # global/nonlocal writes are module/outer-scope effects, and
            # loop induction variables are iteration plumbing (ruff would
            # file them under B007) — neither is an unused LOCAL.
            r = fscopes[-1]
            if t in (ast.Global, ast.Nonlocal):
                r.exempt.update(node.names)
            elif t in (ast.For, ast.AsyncFor):
                r.exempt.update(n.id for n in ast.walk(node.target) if isinstance(n, ast.Name))
            elif t is ast.comprehension:
                r.exempt.update(n.id for n in ast.walk(node.target) if isinstance(n, ast.Name))
            elif t in (ast.With, ast.AsyncWith):
                # `with ... as x:` targets are context handles pyflakes/ruff
                # never file under F841 (e.g. pytest.raises(...) as exc).
                for item in node.items:
                    if item.optional_vars is not None:
                        r.exempt.update(n.id for n in ast.walk(item.optional_vars) if isinstance(n, ast.Name))
            elif t is ast.Assign:
                # Tuple-unpack targets document structure — exempt them.
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Tuple, ast.List)):
                        r.exempt.update(n.id for n in ast.walk(tgt) if isinstance(n, ast.Name))
        stack.extend((child, fscopes, own) for child in ast.iter_child_nodes(node))
    for r in records:
        r.finalize(findings)
    exported = set(module_all(tree))
    # Names referenced in string annotations / docstring doctests are out
    # of scope; __init__ re-exports are legitimate when listed in __all__.
    is_init = f.path.name == "__init__.py"
    for name, lineno in imports.items():
        if name in used or name == "_":
            continue
        if is_init or name in exported:
            continue
        # A conservative text check catches usage forms the AST walk does
        # not model (e.g. inside f-string format specs).
        if len(re.findall(rf"\b{re.escape(name)}\b", f.text)) > 1:
            continue
        findings.append(Finding("F401", f.rel, lineno, f"'{name}' imported but unused"))
    defined = top_level_defs(tree)
    for name in exported:
        if name not in defined:
            findings.append(Finding("F822", f.rel, 1, f"undefined name '{name}' in __all__"))


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    for f in ctx.files:
        for i, line in enumerate(f.lines, 1):
            if line != line.rstrip():
                findings.append(Finding("W291", f.rel, i, "trailing whitespace"))
            if line.startswith("\t"):
                findings.append(Finding("W191", f.rel, i, "tab in indentation"))
        if f.tree is not None:
            _check_module(f, findings)
    return findings
