"""Single-parse driver: load + parse every file once, run every pass over
the shared ``Context``, compare against the baseline, report.

Usage (also reachable through the ``scripts/lint.py`` shim):

    python -m scripts.analyze [paths...] [options]

Options:
    --rule CODE[,CODE]   run only the named rule(s); baseline comparison is
                         scoped to them
    --changed-only       fast path for pre-commit: analyze only the files
                         git reports changed (staged, unstaged, untracked),
                         running only the passes that are sound on a
                         partial context (each pass declares FILE_SCOPED)
    --json               machine-readable report on stdout (findings with a
                         baselined flag, plus new/stale arrays) for CI
                         annotation
    --json-out FILE      additionally write the JSON report to FILE (the
                         artifact bench.py folds into its provenance row)
    --budget SECONDS     fail (exit 1) when the whole run exceeds SECONDS —
                         the make-check guarantee that analysis never
                         becomes the slow part of the gate
    --write-baseline     pin the current findings as the new baseline
                         (reasons start as a review placeholder)
    --no-baseline        report raw findings, ignore baseline.json
    --list-rules         print the rule catalogue and exit

Exit status: 0 iff there are no NEW findings, no STALE baseline entries,
and the budget (when given) was met.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import time

from . import catalogues, determinism, excp, exports, hygiene, jitc, jitpure, locks, modelcheck, protocol, shapes
from .baseline import BASELINE_PATH, compare, load_baseline, write_baseline
from .core import DEFAULT_PATHS, ROOT, Context, Finding, load_files

# Fixed pass order: cheap mechanical hygiene first, repo-invariant passes
# last (their reports are the ones a human digs into).  protocol precedes
# modelcheck so spec parse errors surface as PROT before MODL explores.
PASSES = (hygiene, exports, catalogues, excp, locks, jitpure, jitc, determinism, shapes, protocol, modelcheck)


def all_codes() -> dict[str, str]:
    """Every registered rule code -> one-line rationale (the ANLZ surface)."""
    out: dict[str, str] = {}
    for p in PASSES:
        out.update(p.CODES)
    return out


def file_scoped_codes() -> set[str]:
    """Rules sound on a partial file set (the --changed-only pass subset).
    E999 rides along: it is reported per file by the driver itself."""
    out = {"E999"}
    for p in PASSES:
        if getattr(p, "FILE_SCOPED", False):
            out.update(p.CODES)
    return out


def changed_paths(root: pathlib.Path = ROOT) -> list[str] | None:
    """Repo-relative paths git reports as changed (unstaged + staged +
    untracked), filtered to the analyzed extensions.  None when git itself
    fails (not a repo, no git) — the caller falls back to a full run."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=all"],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    paths: list[str] = []
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: analyze the new side
            path = path.split(" -> ", 1)[1]
        path = path.strip('"')
        if path.endswith(".py") or path == "README.md":
            if (root / path).exists():
                paths.append(path)
    return sorted(set(paths))


def run_passes(ctx: Context, rules: set[str] | None = None, file_scoped_only: bool = False) -> list[Finding]:
    findings: list[Finding] = []
    for f in ctx.files:
        if f.tree is None:
            try:
                import ast

                ast.parse(f.text, filename=str(f.path))
            except SyntaxError as e:
                findings.append(Finding("E999", f.rel, e.lineno or 1, f"syntax error: {e.msg}"))
    for p in PASSES:
        if rules is not None and not (set(p.CODES) & rules):
            continue
        if file_scoped_only and not getattr(p, "FILE_SCOPED", False):
            continue
        findings.extend(p.run(ctx))
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def main(argv: list[str]) -> int:
    t0 = time.perf_counter()
    args = list(argv)
    rules: set[str] | None = None
    as_json = write = no_baseline = changed_only = False
    json_out: str | None = None
    budget: float | None = None
    paths: list[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--rule":
            i += 1
            if i >= len(args):
                print("--rule requires a CODE argument", file=sys.stderr)
                return 2
            rules = (rules or set()) | {c.strip().upper() for c in args[i].split(",") if c.strip()}
        elif a.startswith("--rule="):
            rules = (rules or set()) | {c.strip().upper() for c in a.split("=", 1)[1].split(",") if c.strip()}
        elif a == "--json":
            as_json = True
        elif a == "--json-out":
            i += 1
            if i >= len(args):
                print("--json-out requires a FILE argument", file=sys.stderr)
                return 2
            json_out = args[i]
        elif a.startswith("--json-out="):
            json_out = a.split("=", 1)[1]
        elif a == "--budget":
            i += 1
            if i >= len(args):
                print("--budget requires a SECONDS argument", file=sys.stderr)
                return 2
            budget = float(args[i])
        elif a.startswith("--budget="):
            budget = float(a.split("=", 1)[1])
        elif a == "--changed-only":
            changed_only = True
        elif a == "--write-baseline":
            write = True
        elif a == "--no-baseline":
            no_baseline = True
        elif a == "--list-rules":
            for code, rationale in sorted(all_codes().items()):
                print(f"{code}  {rationale}")
            return 0
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        elif a.startswith("-"):
            print(f"unknown option {a!r}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    if rules is not None:
        unknown = rules - set(all_codes())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))} (see --list-rules)", file=sys.stderr)
            return 2

    if changed_only:
        changed = changed_paths()
        if changed is None:
            print("analyze: --changed-only could not read git status; running the full set", file=sys.stderr)
        else:
            # Only files under the analyzed roots — a stray .py elsewhere in
            # the repo is not this gate's business.
            roots = tuple(p for p in DEFAULT_PATHS if (ROOT / p).is_dir())
            files = tuple(p for p in DEFAULT_PATHS if not (ROOT / p).is_dir())
            paths = [
                p
                for p in changed
                if p.endswith(".py") and (p.startswith(tuple(r + "/" for r in roots)) or p in files)
            ]
            if not paths:
                print("analyze: 0 changed files, nothing to check")
                return 0
            # Restrict the rule set to passes sound on a partial context, so
            # the baseline comparison cannot cry NEW or STALE on rules that
            # did not (or could not correctly) run.
            scoped = file_scoped_codes()
            rules = (rules & scoped) if rules is not None else scoped

    files = load_files(paths or DEFAULT_PATHS)
    readme = (ROOT / "README.md").read_text() if (ROOT / "README.md").exists() else ""
    ctx = Context(files=files, root=ROOT, readme=readme)
    findings = run_passes(ctx, rules, file_scoped_only=changed_only)

    if write:
        write_baseline(findings)
        print(f"analyze: wrote {len(findings)} baseline entr{'y' if len(findings) == 1 else 'ies'} to {BASELINE_PATH}")
        return 0

    if no_baseline:
        entries: list[dict] = []
    else:
        entries = load_baseline()
    # Scope the stale check to the analyzed files (plus README, which the
    # catalogue gates report against) so a partial run cannot cry stale.
    scope_paths = {f.rel for f in files} | {"README.md"}
    new, stale, baselined = compare(findings, entries, rules=rules, paths=scope_paths)

    elapsed = time.perf_counter() - t0
    over_budget = budget is not None and elapsed > budget

    report = None
    if as_json or json_out:
        report = {
            "files": len(files),
            "findings": [
                {**f.__dict__, "baselined": f.key in {b.key for b in baselined}} for f in findings
            ],
            "new": [f.__dict__ for f in new],
            "stale": stale,
            "elapsed_s": round(elapsed, 3),
            "budget_s": budget,
            "changed_only": changed_only,
            # Per-machine model-check stats (empty when MODL did not run,
            # e.g. --changed-only or a --rule subset); bench.py provenance.
            "modelcheck": dict(modelcheck.LAST_STATS),
            # Bucket/hotpath contract coverage (empty when JITC did not
            # run); bench.py provenance.
            "jitc": dict(jitc.LAST_STATS),
        }
    if json_out and report is not None:
        pathlib.Path(json_out).write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
    if as_json and report is not None:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(
                f"{e['path']}:1: STALE baseline entry — {e['rule']} \"{e['message']}\" no longer found; "
                f"remove it from scripts/analyze/baseline.json (reason was: {e['reason']})"
            )
        mode = " (changed-only)" if changed_only else ""
        print(
            f"analyze{mode}: {len(files)} files, {len(findings)} findings "
            f"({len(baselined)} baselined), {len(new)} new, {len(stale)} stale, {elapsed:.2f}s"
        )
    if over_budget:
        print(
            f"analyze: BUDGET EXCEEDED — {elapsed:.2f}s > {budget:.2f}s; the analysis gate must stay "
            "the fast part of make check (profile the passes, see scripts/analyze/exports.py for the pattern)",
            file=sys.stderr,
        )
    return 1 if new or stale or over_budget else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
