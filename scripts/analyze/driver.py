"""Single-parse driver: load + parse every file once, run every pass over
the shared ``Context``, compare against the baseline, report.

Usage (also reachable through the ``scripts/lint.py`` shim):

    python -m scripts.analyze [paths...] [options]

Options:
    --rule CODE[,CODE]   run only the named rule(s); baseline comparison is
                         scoped to them
    --json               machine-readable report on stdout (findings with a
                         baselined flag, plus new/stale arrays) for CI
                         annotation
    --write-baseline     pin the current findings as the new baseline
                         (reasons start as a review placeholder)
    --no-baseline        report raw findings, ignore baseline.json
    --list-rules         print the rule catalogue and exit

Exit status: 0 iff there are no NEW findings and no STALE baseline entries.
"""

from __future__ import annotations

import json
import sys

from . import catalogues, determinism, exports, hygiene, jitpure, locks
from .baseline import BASELINE_PATH, compare, load_baseline, write_baseline
from .core import DEFAULT_PATHS, ROOT, Context, Finding, load_files

# Fixed pass order: cheap mechanical hygiene first, repo-invariant passes
# last (their reports are the ones a human digs into).
PASSES = (hygiene, exports, catalogues, locks, jitpure, determinism)


def all_codes() -> dict[str, str]:
    """Every registered rule code -> one-line rationale (the ANLZ surface)."""
    out: dict[str, str] = {}
    for p in PASSES:
        out.update(p.CODES)
    return out


def run_passes(ctx: Context, rules: set[str] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for f in ctx.files:
        if f.tree is None:
            try:
                import ast

                ast.parse(f.text, filename=str(f.path))
            except SyntaxError as e:
                findings.append(Finding("E999", f.rel, e.lineno or 1, f"syntax error: {e.msg}"))
    for p in PASSES:
        if rules is not None and not (set(p.CODES) & rules):
            continue
        findings.extend(p.run(ctx))
    if rules is not None:
        findings = [f for f in findings if f.rule in rules]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.message))


def main(argv: list[str]) -> int:
    args = list(argv)
    rules: set[str] | None = None
    as_json = write = no_baseline = False
    paths: list[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--rule":
            i += 1
            if i >= len(args):
                print("--rule requires a CODE argument", file=sys.stderr)
                return 2
            rules = (rules or set()) | {c.strip().upper() for c in args[i].split(",") if c.strip()}
        elif a.startswith("--rule="):
            rules = (rules or set()) | {c.strip().upper() for c in a.split("=", 1)[1].split(",") if c.strip()}
        elif a == "--json":
            as_json = True
        elif a == "--write-baseline":
            write = True
        elif a == "--no-baseline":
            no_baseline = True
        elif a == "--list-rules":
            for code, rationale in sorted(all_codes().items()):
                print(f"{code}  {rationale}")
            return 0
        elif a in ("-h", "--help"):
            print(__doc__)
            return 0
        elif a.startswith("-"):
            print(f"unknown option {a!r}", file=sys.stderr)
            return 2
        else:
            paths.append(a)
        i += 1

    if rules is not None:
        unknown = rules - set(all_codes())
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))} (see --list-rules)", file=sys.stderr)
            return 2

    files = load_files(paths or DEFAULT_PATHS)
    readme = (ROOT / "README.md").read_text() if (ROOT / "README.md").exists() else ""
    ctx = Context(files=files, root=ROOT, readme=readme)
    findings = run_passes(ctx, rules)

    if write:
        write_baseline(findings)
        print(f"analyze: wrote {len(findings)} baseline entr{'y' if len(findings) == 1 else 'ies'} to {BASELINE_PATH}")
        return 0

    if no_baseline:
        entries: list[dict] = []
    else:
        entries = load_baseline()
    # Scope the stale check to the analyzed files (plus README, which the
    # catalogue gates report against) so a partial run cannot cry stale.
    scope_paths = {f.rel for f in files} | {"README.md"}
    new, stale, baselined = compare(findings, entries, rules=rules, paths=scope_paths)

    if as_json:
        report = {
            "files": len(files),
            "findings": [
                {**f.__dict__, "baselined": f.key in {b.key for b in baselined}} for f in findings
            ],
            "new": [f.__dict__ for f in new],
            "stale": stale,
        }
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(
                f"{e['path']}:1: STALE baseline entry — {e['rule']} \"{e['message']}\" no longer found; "
                f"remove it from scripts/analyze/baseline.json (reason was: {e['reason']})"
            )
        print(
            f"analyze: {len(files)} files, {len(findings)} findings "
            f"({len(baselined)} baselined), {len(new)} new, {len(stale)} stale"
        )
    return 1 if new or stale else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
