"""Shared analysis substrate: one parse per file, consumed by every pass.

``SourceFile`` carries the path, raw text, split lines, and the parsed AST
(``None`` when the file does not parse — the driver reports E999 and the
passes skip it).  ``Context`` is the whole-repo view a pass runs against;
cross-file rules (DEAD, THRD's lock-order graph, JAXP's call graph) read it
directly instead of re-globbing.
"""

from __future__ import annotations

import ast
import pathlib
from dataclasses import dataclass

ROOT = pathlib.Path(__file__).resolve().parent.parent.parent
DEFAULT_PATHS = ["tpu_scheduler", "tests", "bench.py", "__graft_entry__.py", "scripts"]


@dataclass(frozen=True)
class Finding:
    """One rule violation.  Identity for baseline matching is
    ``(rule, path, message)`` — deliberately line-free, so editing an
    unrelated part of a file cannot stale a pinned finding."""

    rule: str
    path: str  # repo-relative posix path
    line: int
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclass
class SourceFile:
    path: pathlib.Path
    rel: str
    text: str
    lines: list[str]
    tree: ast.Module | None  # None => syntax error (E999, reported by driver)

    def in_package(self, *parts: str) -> bool:
        return tuple(self.rel.split("/")[: len(parts)]) == parts


@dataclass
class Context:
    files: list[SourceFile]
    root: pathlib.Path
    readme: str

    def parsed(self) -> list[SourceFile]:
        return [f for f in self.files if f.tree is not None]


def iter_py(paths: list[str], root: pathlib.Path = ROOT) -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for p in paths:
        path = root / p
        if path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            out.append(path)
    return out


def load_files(paths: list[str], root: pathlib.Path = ROOT) -> list[SourceFile]:
    files: list[SourceFile] = []
    for f in iter_py(paths, root):
        text = f.read_text()
        try:
            tree = ast.parse(text, filename=str(f))
        except SyntaxError:
            tree = None
        files.append(
            SourceFile(path=f, rel=f.relative_to(root).as_posix(), text=text, lines=text.splitlines(), tree=tree)
        )
    return files


# -- small AST helpers shared by several passes -----------------------------


def module_all(tree: ast.Module) -> list[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and isinstance(node.value, (ast.List, ast.Tuple)):
                    return [e.value for e in node.value.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def top_level_defs(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Tuple):
                    names.update(e.id for e in t.elts if isinstance(e, ast.Name))
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name.split(".")[0])
    return names


def self_attr_path(node: ast.expr) -> str | None:
    """Dotted attribute path rooted at ``self`` (``self._a._b`` -> "_a._b"),
    or None when the expression is not a pure self-attribute chain."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name) and cur.id == "self" and parts:
        return ".".join(reversed(parts))
    return None
