"""Baseline handling — pre-existing findings are PINNED, never suppressed.

``baseline.json`` holds one entry per accepted finding: its identity
``(rule, path, message)`` plus a human reason for deferring the fix.  The
driver fails on any NEW finding (not in the baseline) and on any STALE
entry (in the baseline but no longer found) — so the baseline can only
shrink, and a fix is forced to also retire its pin.  Line numbers are
deliberately not part of identity: editing an unrelated part of a file
must not churn the baseline.
"""

from __future__ import annotations

import json
import pathlib

from .core import Finding

BASELINE_PATH = pathlib.Path(__file__).resolve().parent / "baseline.json"
BASELINE_VERSION = 1


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> list[dict]:
    if not path.exists():
        return []
    data = json.loads(path.read_text())
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: unsupported baseline version {data.get('version')!r}")
    entries = data.get("entries", [])
    for e in entries:
        for field in ("rule", "path", "message", "reason"):
            if not isinstance(e.get(field), str) or not e[field]:
                raise ValueError(f"{path}: baseline entry missing/empty {field!r}: {e}")
    return entries


def write_baseline(findings: list[Finding], path: pathlib.Path = BASELINE_PATH) -> None:
    entries = [
        {
            "rule": f.rule,
            "path": f.path,
            "message": f.message,
            "reason": "pinned pre-existing finding — review and either fix or justify",
        }
        for f in sorted(set(findings), key=lambda f: f.key)
    ]
    path.write_text(json.dumps({"version": BASELINE_VERSION, "entries": entries}, indent=2) + "\n")


def compare(
    findings: list[Finding],
    entries: list[dict],
    rules: set[str] | None = None,
    paths: set[str] | None = None,
) -> tuple[list[Finding], list[dict], list[Finding]]:
    """Split into (new findings, stale entries, baselined findings).

    ``rules``/``paths`` restrict the comparison scope — a ``--rule`` or
    explicit-path run must not report out-of-scope baseline entries stale.
    """

    def in_scope(rule: str, path: str) -> bool:
        if rules is not None and rule not in rules:
            return False
        if paths is not None and path not in paths:
            return False
        return True

    pinned = {(e["rule"], e["path"], e["message"]) for e in entries if in_scope(e["rule"], e["path"])}
    found_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in pinned]
    stale = [
        e
        for e in entries
        if in_scope(e["rule"], e["path"]) and (e["rule"], e["path"], e["message"]) not in found_keys
    ]
    baselined = [f for f in findings if f.key in pinned]
    return new, stale, baselined
