"""README drift gates — a name that exists in code but not in its README
catalogue fails the build, so the docs cannot rot silently.

METR  — every ``scheduler_*`` metric-name literal used in the package must
        appear in the README Observability metric catalogue.
SIMC  — every registered scenario name, chaos knob, and scorecard field in
        ``tpu_scheduler/sim/`` must appear in the README "Simulation &
        chaos" catalogue.
ANLZ  — every rule code this analysis suite registers must appear in the
        README "Static analysis" catalogue (the gate gating its own docs —
        same pattern as METR/SIMC).
RESC  — every backoff failure class, circuit-breaker state, and breaker
        config knob in ``runtime/resilience.py`` must appear in the README
        "Resilience" catalogue.
TOPO  — every interconnect distance level (name + label key,
        ``topology/model.DEFAULT_LEVEL_KEYS``), locality scoring knob
        (``topology/locality.SCORING_KNOBS``), and topology-exercising sim
        scenario (a registry entry whose WorkloadSpec sets
        slice_size/rack_size/rack_fail_times) must appear in the README
        "Topology & gang placement" catalogue.
REPL  — every shard/replica lease-name prefix (``runtime/shards.py``
        ``*_LEASE_PREFIX`` constants), availability-scorecard field
        (``sim/multi.AVAILABILITY_FIELDS``), and multi-replica sim scenario
        (a registry entry passing ``replicas=``) must appear in the README
        "Multi-replica & failover" catalogue.
PROF  — every profiler span name (``utils/profiler.SPAN_CATALOGUE``) and
        SLO tier (``utils/profiler.SLO_TIERS``) must appear in the README
        "Profiling" catalogue; metric names ride the METR gate as usual.
DLTA  — every full-wave escalation trigger
        (``delta/engine.ESCALATION_REASONS``) and incremental-scorecard
        field (``sim/scorecard.INCREMENTAL_FIELDS``) must appear in the
        README "Incremental scheduling" catalogue.
REBL  — every migration reason / skip reason / config knob of the
        background rebalancer (``rebalance/planner.MIGRATION_REASONS``,
        ``SKIP_REASONS``, ``RebalanceConfig`` fields), every rebalance-
        scorecard field (``sim/scorecard.REBALANCE_FIELDS``), and every
        rebalance-exercising sim scenario (a registry entry passing
        ``rebalance=``) must appear in the README "Rebalancing &
        defragmentation" catalogue.
FLET  — every multi-mesh fleet keyer mode (``fleet/keyer.KEYER_MODES``),
        gang-reservation state (``fleet/reservation.RESERVATION_STATES``),
        and fleet lease name/prefix (``fleet/reservation.
        GANG_RESERVATION_PREFIX``, ``fleet/resize.SHARD_MAP_LEASE``) must
        appear in the README "Multi-mesh fleet" catalogue.
LERN  — every policy-objective component (``learn/objective.
        OBJECTIVE_COMPONENTS``), policy-scorecard field (``learn/objective.
        POLICY_FIELDS``), observation field (``learn/env.
        OBSERVATION_FIELDS``), action knob (``learn/env.ACTION_KNOBS``),
        search knob (``learn/search.SearchConfig`` fields), and artifact
        field (``models/profiles.ARTIFACT_FIELDS``) must appear in the
        README "Learned policy & tuning" catalogue.
LATN  — every time-to-bind waterfall segment (``utils/events.SEGMENTS``)
        and latency-scorecard field (``sim/scorecard.LATENCY_FIELDS``)
        must appear in the README "Latency & time-to-bind" catalogue.
ELAS  — every autoscaler skip reason / config knob (``autoscale/policy.
        SKIP_REASONS``, ``AutoscaleConfig`` fields), default-catalog SKU
        (``autoscale/provider`` ``InstanceSKU(name=...)`` literals),
        elasticity-scorecard field (``sim/scorecard.ELASTICITY_FIELDS``),
        and elasticity-exercising sim scenario (a registry entry passing
        ``autoscale=``) must appear in the README "Autoscaling &
        elasticity" catalogue.
FUZZ  — every fault-op kind / plan-JSON field / base workload
        (``sim/fuzz/plan.FAULT_OPS``, ``PLAN_FIELDS``, ``OP_FIELDS``,
        ``BASE_WORKLOADS`` keys), coverage facet
        (``sim/fuzz/coverage.STATE_FACETS``), corpus-entry field
        (``sim/fuzz/corpus.ENTRY_FIELDS``), and convergence-scorecard field
        (``sim/scorecard.CONVERGENCE_FIELDS``) must appear in the README
        "Chaos fuzzing" catalogue.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding

CODES = {
    "METR": "a scheduler_* metric used in the package but missing from the README metric catalogue",
    "SIMC": "a sim scenario/chaos knob/scorecard field missing from the README simulation catalogue",
    "ANLZ": "an analysis rule code missing from the README static-analysis catalogue",
    "RESC": "a resilience backoff class/breaker state/config knob missing from the README Resilience catalogue",
    "TOPO": "a topology distance level/label key/scoring knob/scenario missing from the README \"Topology & gang placement\" catalogue",
    "REPL": "a shard lease prefix/availability field/multi-replica scenario missing from the README \"Multi-replica & failover\" catalogue",
    "PROF": "a profiler span name/SLO tier missing from the README \"Profiling\" catalogue",
    "DLTA": "a delta-engine escalation trigger/incremental scorecard field missing from the README \"Incremental scheduling\" catalogue",
    "REBL": "a rebalancer migration/skip reason/config knob/scorecard field/scenario missing from the README \"Rebalancing & defragmentation\" catalogue",
    "FLET": "a fleet keyer mode/reservation state/lease name missing from the README \"Multi-mesh fleet\" catalogue",
    "LERN": "a policy objective component/observation field/action knob/search knob/artifact field missing from the README \"Learned policy & tuning\" catalogue",
    "LATN": "a time-to-bind waterfall segment/latency scorecard field missing from the README \"Latency & time-to-bind\" catalogue",
    "ELAS": "an autoscaler skip reason/config knob/catalog SKU/scorecard field/scenario missing from the README \"Autoscaling & elasticity\" catalogue",
    "FUZZ": "a fault-op kind/plan field/base workload/coverage facet/corpus field/convergence field missing from the README \"Chaos fuzzing\" catalogue",
}

# Code→README direction only: a partial (--changed-only) context can merely
# under-report (names from a subset of files), never false-positive.
FILE_SCOPED = True

_METRIC_RE = re.compile(r'"(scheduler_[a-z0-9_]+)"')


def _run_metr(ctx: Context) -> list[Finding]:
    names: set[str] = set()
    for f in ctx.files:
        if f.in_package("tpu_scheduler"):
            names.update(_METRIC_RE.findall(f.text))
    return [
        Finding(
            "METR",
            "README.md",
            1,
            f"metric '{name}' is used in tpu_scheduler/ but missing from the README metric catalogue",
        )
        for name in sorted(names)
        if name not in ctx.readme
    ]


def _run_simc(ctx: Context) -> list[Finding]:
    catalogue: list[tuple[str, str]] = []  # (kind, name)
    for f in ctx.parsed():
        if not f.in_package("tpu_scheduler", "sim"):
            continue
        if f.path.name == "scenarios.py":
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Scenario":
                    for kw in node.keywords:
                        if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                            catalogue.append(("scenario", kw.value.value))
        elif f.path.name == "chaos.py":
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef) and node.name in ("ChaosConfig", "ChaosWindow"):
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                            catalogue.append(("chaos knob", stmt.target.id))
        elif f.path.name == "scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Name)
                            and t.id == "SCORECARD_FIELDS"
                            and isinstance(node.value, (ast.Tuple, ast.List))
                        ):
                            for e in node.value.elts:
                                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                                    catalogue.append(("scorecard field", e.value))
    return [
        Finding(
            "SIMC",
            "README.md",
            1,
            f"{kind} '{name}' exists in tpu_scheduler/sim/ but is missing from the README \"Simulation & chaos\" catalogue",
        )
        for kind, name in sorted(set(catalogue))
        if name not in ctx.readme
    ]


def _run_anlz(ctx: Context) -> list[Finding]:
    from .driver import all_codes  # late import: driver owns the registry

    return [
        Finding(
            "ANLZ",
            "README.md",
            1,
            f"analysis rule '{code}' is enforced by scripts/analyze but missing from the README \"Static analysis\" catalogue",
        )
        for code in sorted(all_codes())
        if code not in ctx.readme
    ]


def _run_resc(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel != "tpu_scheduler/runtime/resilience.py":
            continue
        for node in f.tree.body:
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                targets: list[tuple[str, object]] = [(node.target.id, node.value)]
            elif isinstance(node, ast.Assign):
                targets = [(t.id, node.value) for t in node.targets if isinstance(t, ast.Name)]
            elif isinstance(node, ast.ClassDef) and node.name == "BreakerConfig":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                        tokens.append(("breaker knob", stmt.target.id))
                continue
            else:
                continue
            for name, value in targets:
                if name == "DEFAULT_POLICIES" and isinstance(value, ast.Dict):
                    for k in value.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            tokens.append(("backoff class", k.value))
                elif name == "STATES" and isinstance(value, (ast.Tuple, ast.List)):
                    for e in value.elts:
                        if isinstance(e, ast.Constant) and isinstance(e.value, str):
                            tokens.append(("breaker state", e.value))
    return [
        Finding(
            "RESC",
            "README.md",
            1,
            f"{kind} '{name}' exists in runtime/resilience.py but is missing from the README \"Resilience\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _topo_tuple_entries(value, kinds) -> list[tuple[str, str]]:
    """String constants of a literal tuple/list, labeled positionally (flat
    tuples label every element with kinds[0]; pair tuples label per slot)."""
    out: list[tuple[str, str]] = []
    if not isinstance(value, (ast.Tuple, ast.List)):
        return out
    for e in value.elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append((kinds[0], e.value))
        elif isinstance(e, (ast.Tuple, ast.List)):
            for kind, el in zip(kinds, e.elts):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.append((kind, el.value))
    return out


def _run_topo(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/topology/model.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "DEFAULT_LEVEL_KEYS":
                            tokens.extend(_topo_tuple_entries(node.value, ("distance level", "level label key")))
        elif f.rel == "tpu_scheduler/topology/locality.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "SCORING_KNOBS":
                            tokens.extend(_topo_tuple_entries(node.value, ("scoring knob",)))
        elif f.rel == "tpu_scheduler/sim/scenarios.py":
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Scenario"):
                    continue
                name = None
                topo = False
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        name = kw.value.value
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "WorkloadSpec"
                        and any(k.arg in ("slice_size", "rack_size", "rack_fail_times") for k in sub.keywords)
                    ):
                        topo = True
                if name and topo:
                    tokens.append(("topology scenario", name))
    return [
        Finding(
            "TOPO",
            "README.md",
            1,
            f"{kind} '{name}' exists in the topology subsystem but is missing from the README "
            f"\"Topology & gang placement\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_repl(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/runtime/shards.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Name)
                            and t.id.endswith("_LEASE_PREFIX")
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)
                        ):
                            tokens.append(("lease prefix", node.value.value))
        elif f.rel == "tpu_scheduler/sim/multi.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "AVAILABILITY_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("availability field",)))
        elif f.rel == "tpu_scheduler/sim/scenarios.py":
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Scenario"):
                    continue
                name = None
                multi = False
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        name = kw.value.value
                    elif kw.arg == "replicas":
                        multi = True
                if name and multi:
                    tokens.append(("multi-replica scenario", name))
    return [
        Finding(
            "REPL",
            "README.md",
            1,
            f"{kind} '{name}' exists in the sharded control plane but is missing from the README "
            f"\"Multi-replica & failover\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_prof(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel != "tpu_scheduler/utils/profiler.py":
            continue
        for node in f.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "SPAN_CATALOGUE":
                    tokens.extend(_topo_tuple_entries(node.value, ("profiler span",)))
                elif t.id == "SLO_TIERS":
                    # Rows are (name, floor, target) tuples; only the NAME
                    # slot is a catalogue token (floors/targets are numbers).
                    tokens.extend(_topo_tuple_entries(node.value, ("SLO tier",)))
    return [
        Finding(
            "PROF",
            "README.md",
            1,
            f"{kind} '{name}' exists in utils/profiler.py but is missing from the README \"Profiling\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_dlta(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/delta/engine.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "ESCALATION_REASONS":
                            tokens.extend(_topo_tuple_entries(node.value, ("escalation trigger",)))
        elif f.rel == "tpu_scheduler/sim/scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "INCREMENTAL_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("incremental scorecard field",)))
    return [
        Finding(
            "DLTA",
            "README.md",
            1,
            f"{kind} '{name}' exists in the incremental delta engine but is missing from the README "
            f"\"Incremental scheduling\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_rebl(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/rebalance/planner.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if t.id == "MIGRATION_REASONS":
                            tokens.extend(_topo_tuple_entries(node.value, ("migration reason",)))
                        elif t.id == "SKIP_REASONS":
                            tokens.extend(_topo_tuple_entries(node.value, ("skip reason",)))
                elif isinstance(node, ast.ClassDef) and node.name == "RebalanceConfig":
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                            tokens.append(("rebalance knob", stmt.target.id))
        elif f.rel == "tpu_scheduler/sim/scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "REBALANCE_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("rebalance scorecard field",)))
        elif f.rel == "tpu_scheduler/sim/scenarios.py":
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Scenario"):
                    continue
                name = None
                rebalancing = False
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        name = kw.value.value
                    elif kw.arg == "rebalance":
                        rebalancing = True
                if name and rebalancing:
                    tokens.append(("rebalance scenario", name))
    return [
        Finding(
            "REBL",
            "README.md",
            1,
            f"{kind} '{name}' exists in the background rebalancer but is missing from the README "
            f"\"Rebalancing & defragmentation\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_flet(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/fleet/keyer.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "KEYER_MODES":
                            tokens.extend(_topo_tuple_entries(node.value, ("keyer mode",)))
        elif f.rel == "tpu_scheduler/fleet/reservation.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if t.id == "RESERVATION_STATES":
                            tokens.extend(_topo_tuple_entries(node.value, ("reservation state",)))
                        elif (
                            t.id == "GANG_RESERVATION_PREFIX"
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)
                        ):
                            tokens.append(("fleet lease prefix", node.value.value))
        elif f.rel == "tpu_scheduler/fleet/resize.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Name)
                            and t.id == "SHARD_MAP_LEASE"
                            and isinstance(node.value, ast.Constant)
                            and isinstance(node.value.value, str)
                        ):
                            tokens.append(("fleet lease name", node.value.value))
    return [
        Finding(
            "FLET",
            "README.md",
            1,
            f"{kind} '{name}' exists in the multi-mesh fleet layer but is missing from the README "
            f"\"Multi-mesh fleet\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_lern(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/learn/objective.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if t.id == "OBJECTIVE_COMPONENTS":
                            tokens.extend(_topo_tuple_entries(node.value, ("objective component",)))
                        elif t.id == "POLICY_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("policy scorecard field",)))
        elif f.rel == "tpu_scheduler/learn/env.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if t.id == "OBSERVATION_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("observation field",)))
                        elif t.id == "ACTION_KNOBS":
                            tokens.extend(_topo_tuple_entries(node.value, ("action knob",)))
        elif f.rel == "tpu_scheduler/learn/search.py":
            for node in f.tree.body:
                if isinstance(node, ast.ClassDef) and node.name == "SearchConfig":
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                            tokens.append(("search knob", stmt.target.id))
        elif f.rel == "tpu_scheduler/models/profiles.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "ARTIFACT_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("artifact field",)))
    return [
        Finding(
            "LERN",
            "README.md",
            1,
            f"{kind} '{name}' exists in the policy-learning subsystem but is missing from the README "
            f"\"Learned policy & tuning\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_latn(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/utils/events.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "SEGMENTS":
                            tokens.extend(_topo_tuple_entries(node.value, ("waterfall segment",)))
        elif f.rel == "tpu_scheduler/sim/scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "LATENCY_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("latency scorecard field",)))
    return [
        Finding(
            "LATN",
            "README.md",
            1,
            f"{kind} '{name}' exists in the time-to-bind waterfall but is missing from the README "
            f"\"Latency & time-to-bind\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_elas(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/autoscale/policy.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "SKIP_REASONS":
                            tokens.extend(_topo_tuple_entries(node.value, ("autoscale skip reason",)))
                elif isinstance(node, ast.ClassDef) and node.name == "AutoscaleConfig":
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                            tokens.append(("autoscale knob", stmt.target.id))
        elif f.rel == "tpu_scheduler/autoscale/provider.py":
            # Catalog SKUs: every InstanceSKU(name="...") literal — the
            # default catalog's rows must be documented by name.
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "InstanceSKU"):
                    continue
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        tokens.append(("catalog SKU", kw.value.value))
        elif f.rel == "tpu_scheduler/sim/scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "ELASTICITY_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("elasticity scorecard field",)))
        elif f.rel == "tpu_scheduler/sim/scenarios.py":
            for node in ast.walk(f.tree):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and node.func.id == "Scenario"):
                    continue
                name = None
                autoscaling = False
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                        name = kw.value.value
                    elif kw.arg == "autoscale":
                        autoscaling = True
                if name and autoscaling:
                    tokens.append(("elasticity scenario", name))
    return [
        Finding(
            "ELAS",
            "README.md",
            1,
            f"{kind} '{name}' exists in the closed-loop autoscaler but is missing from the README "
            f"\"Autoscaling & elasticity\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def _run_fuzz(ctx: Context) -> list[Finding]:
    tokens: list[tuple[str, str]] = []
    for f in ctx.parsed():
        if f.rel == "tpu_scheduler/sim/fuzz/plan.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not isinstance(t, ast.Name):
                            continue
                        if t.id == "FAULT_OPS":
                            tokens.extend(_topo_tuple_entries(node.value, ("fault op",)))
                        elif t.id == "PLAN_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("plan field",)))
                        elif t.id == "OP_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("plan op field",)))
                        elif t.id == "BASE_WORKLOADS" and isinstance(node.value, ast.Dict):
                            for k in node.value.keys:
                                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                                    tokens.append(("fuzz base workload", k.value))
        elif f.rel == "tpu_scheduler/sim/fuzz/coverage.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "STATE_FACETS":
                            tokens.extend(_topo_tuple_entries(node.value, ("coverage facet",)))
        elif f.rel == "tpu_scheduler/sim/fuzz/corpus.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "ENTRY_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("corpus entry field",)))
        elif f.rel == "tpu_scheduler/sim/scorecard.py":
            for node in f.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "CONVERGENCE_FIELDS":
                            tokens.extend(_topo_tuple_entries(node.value, ("convergence scorecard field",)))
    return [
        Finding(
            "FUZZ",
            "README.md",
            1,
            f"{kind} '{name}' exists in the chaos fuzzer but is missing from the README "
            f"\"Chaos fuzzing\" catalogue",
        )
        for kind, name in sorted(set(tokens))
        if name not in ctx.readme
    ]


def run(ctx: Context) -> list[Finding]:
    return (
        _run_metr(ctx)
        + _run_simc(ctx)
        + _run_anlz(ctx)
        + _run_resc(ctx)
        + _run_topo(ctx)
        + _run_repl(ctx)
        + _run_prof(ctx)
        + _run_dlta(ctx)
        + _run_rebl(ctx)
        + _run_flet(ctx)
        + _run_lern(ctx)
        + _run_latn(ctx)
        + _run_elas(ctx)
        + _run_fuzz(ctx)
    )
