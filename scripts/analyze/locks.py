"""THRD — lock discipline over the hand-rolled threaded runtime.

The runtime guards shared state with ``threading.Lock``/``RLock`` by
convention; nothing checked the convention until now.  The contract this
pass enforces:

1. **Guarded attributes.**  An instance attribute assigned in ``__init__``
   with a trailing ``# guarded-by: <lock>`` comment may only be read or
   written inside a ``with self.<lock>:`` block within that class (or in
   ``__init__`` itself — construction happens before the object is
   shared).  ``<lock>`` is a dotted self-attribute path (``_lock``,
   ``_server._lock``).

2. **Holds-lock methods.**  A method whose ``def`` line carries
   ``# holds-lock: <lock>`` declares "callers enter with <lock> held":
   its body counts as guarded, and every ``self.<method>()`` call site in
   the same class must itself hold the lock.

3. **Aliases.**  ``self.cv = threading.Condition(self.lk)`` makes
   ``with self.cv:`` acquire ``lk`` — the checker tracks the alias, so
   condition-variable usage over a shared lock needs no annotation tricks.

4. **Re-entry.**  Acquiring a plain ``threading.Lock`` (not RLock) that is
   already held — directly, or by calling a same-class method that
   acquires it — is a guaranteed deadlock, flagged immediately.

5. **Lock-order graph.**  Every ordered acquisition (a ``with`` nested
   under another, or a call made under lock A into a method of ANY
   analyzed class that acquires lock B) adds edge A -> B to one
   cross-module graph; a cycle is a potential deadlock and fails the
   build.

Soundness stance: lexical and conservative.  Accesses via a non-``self``
receiver (another object's internals) and calls dispatched through
variables are not tracked — false negatives over false positives, like the
rest of this suite.
"""

from __future__ import annotations

import ast
import re

from .core import Context, Finding, SourceFile, self_attr_path

CODES = {
    "THRD": "a guarded-by attribute touched outside its lock, a plain-Lock re-entry, or a lock-order cycle",
}

# Lexical guarded-by/holds-lock checks are per-file; the cross-module
# lock-ORDER graph can only lose edges under a partial (--changed-only)
# context — fewer findings, never false ones — so the fast path may run it.
FILE_SCOPED = True

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_][A-Za-z0-9_.]*)")


class _ClassInfo:
    def __init__(self, sf: SourceFile, node: ast.ClassDef):
        self.sf = sf
        self.node = node
        self.name = node.name
        self.guarded: dict[str, str] = {}  # attr -> canonical lock path
        self.aliases: dict[str, str] = {}  # condition attr -> wrapped lock path
        self.lock_kinds: dict[str, str] = {}  # lock path -> "Lock" | "RLock" | "Condition"
        self.holds: dict[str, set[str]] = {}  # method name -> locks callers must hold
        self.acquires: dict[str, set[str]] = {}  # method name -> locks acquired directly (any depth)

    def canon(self, path: str) -> str:
        return self.aliases.get(path, path)

    def qual(self, path: str) -> str:
        return f"{self.name}.{self.canon(path)}"


def _line_annotation(sf: SourceFile, lineno: int, rx: re.Pattern) -> str | None:
    if 1 <= lineno <= len(sf.lines):
        m = rx.search(sf.lines[lineno - 1])
        if m:
            return m.group(1)
    return None


def _threading_ctor(value: ast.expr) -> tuple[str, ast.expr | None] | None:
    """Match ``threading.Lock()`` / ``Lock()`` / ``threading.Condition(x)``;
    returns (ctor name, first positional arg or None)."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    if name in ("Lock", "RLock", "Condition"):
        return name, (value.args[0] if value.args else None)
    return None


def _scan_init(info: _ClassInfo) -> None:
    # Dataclass-style declarations: class-body ``attr: T = ...`` lines carry
    # the same annotations; the lock kind comes from the type annotation.
    for stmt in info.node.body:
        if not isinstance(stmt, ast.AnnAssign) or not isinstance(stmt.target, ast.Name):
            continue
        attr = stmt.target.id
        ann = stmt.annotation
        kind = ann.attr if isinstance(ann, ast.Attribute) else (ann.id if isinstance(ann, ast.Name) else None)
        if kind in ("Lock", "RLock", "Condition"):
            info.lock_kinds[attr] = kind
        lock = _line_annotation(info.sf, stmt.lineno, _GUARDED_RE)
        if lock is not None:
            info.guarded[attr] = lock
    init = next(
        (n for n in info.node.body if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
        None,
    )
    if init is None:
        return
    for stmt in ast.walk(init):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            target = stmt.target
        else:
            continue
        attr = self_attr_path(target)
        if attr is None or "." in attr:
            continue
        ctor = _threading_ctor(stmt.value)
        if ctor is not None:
            kind, arg = ctor
            info.lock_kinds[attr] = kind
            if kind == "Condition" and arg is not None:
                wrapped = self_attr_path(arg)
                if wrapped is not None:
                    info.aliases[attr] = wrapped
        lock = _line_annotation(info.sf, stmt.lineno, _GUARDED_RE)
        if lock is not None:
            info.guarded[attr] = lock  # canonicalized lazily (aliases may follow)


class _MethodVisitor(ast.NodeVisitor):
    """One method body: track the lexically-held lock set, check guarded
    accesses and holds-lock call sites, record acquisitions and ordered
    pairs for the global graph."""

    def __init__(self, info: _ClassInfo, method: str, held: frozenset, findings, edges, calls_under):
        self.info = info
        self.method = method
        self.held = held  # frozenset of canonical (unqualified) lock paths
        self.findings = findings
        self.edges = edges  # list of (qual_from, qual_to, rel, lineno)
        self.calls_under = calls_under  # (held quals, callee, recv is self, class info, lineno)
        self.acquired: set[str] = set()

    # -- with blocks --------------------------------------------------------

    def visit_With(self, node):
        new = []
        for item in node.items:
            path = self_attr_path(item.context_expr)
            if path is None:
                continue
            canon = self.info.canon(path)
            # Only self-attribute chains that look like locks participate:
            # a known threading ctor, a lock some attribute declares itself
            # guarded by, or the naming convention (covers a lock living on
            # a collaborator, e.g. ``with self._server._lock``).
            last = canon.rsplit(".", 1)[-1]
            if not (
                canon in self.info.lock_kinds
                or canon in self.info.guarded.values()
                or "lock" in last
                or last.endswith("_cv")
            ):
                continue
            if canon in self.held or canon in new:
                if self.info.lock_kinds.get(canon) == "Lock":
                    self.findings.append(
                        Finding(
                            "THRD",
                            self.info.sf.rel,
                            node.lineno,
                            f"{self.info.name}.{self.method} re-acquires plain Lock '{canon}' already held (deadlock)",
                        )
                    )
                continue  # re-entrant RLock/Condition: no new order edge
            for h in list(self.held) + new:
                self.edges.append((self.info.qual(h), self.info.qual(canon), self.info.sf.rel, node.lineno))
            new.append(canon)
            self.acquired.add(canon)
        if new:
            inner = _MethodVisitor(
                self.info, self.method, self.held | frozenset(new), self.findings, self.edges, self.calls_under
            )
            for child in node.body:
                inner.visit(child)
            self.acquired |= inner.acquired
        else:
            for child in node.body:
                self.visit(child)

    visit_AsyncWith = visit_With

    # -- guarded attribute accesses ----------------------------------------

    def visit_Attribute(self, node):
        attr = self_attr_path(node)
        if attr is not None and attr in self.info.guarded:
            lock = self.info.canon(self.info.guarded[attr])
            if lock not in self.held:
                self.findings.append(
                    Finding(
                        "THRD",
                        self.info.sf.rel,
                        node.lineno,
                        f"{self.info.name}.{self.method} touches '{attr}' (guarded-by {lock}) outside 'with self.{lock}'",
                    )
                )
        self.generic_visit(node)

    # -- calls: holds-lock contracts + cross-class order edges -------------

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            callee = fn.attr
            recv_self = isinstance(fn.value, ast.Name) and fn.value.id == "self"
            if recv_self and callee in self.info.holds:
                for lock in sorted(self.info.holds[callee]):
                    if self.info.canon(lock) not in self.held:
                        self.findings.append(
                            Finding(
                                "THRD",
                                self.info.sf.rel,
                                node.lineno,
                                f"{self.info.name}.{self.method} calls {callee}() (holds-lock: {lock}) without holding {lock}",
                            )
                        )
            if self.held:
                quals = frozenset(self.info.qual(h) for h in self.held)
                self.calls_under.append((quals, callee, recv_self, self.info, node.lineno))
        self.generic_visit(node)


def _analyze_class(info: _ClassInfo, findings, edges, calls_under) -> None:
    _scan_init(info)
    # Canonicalize guards declared against a Condition alias.
    for attr, lock in list(info.guarded.items()):
        info.guarded[attr] = info.canon(lock)
    for meth in info.node.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        holds = _line_annotation(info.sf, meth.lineno, _HOLDS_RE)
        if holds is not None:
            info.holds[meth.name] = {info.canon(holds)}
    for meth in info.node.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) or meth.name == "__init__":
            continue
        held = frozenset(info.holds.get(meth.name, ()))
        v = _MethodVisitor(info, meth.name, held, findings, edges, calls_under)
        for child in meth.body:
            v.visit(child)
        info.acquires[meth.name] = v.acquired


def _find_cycles(edges: dict[tuple[str, str], tuple[str, int]]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: list[list[str]] = []
    seen_cycles: set[frozenset] = set()
    state: dict[str, int] = {}  # 0 unvisited / 1 on stack / 2 done
    stack: list[str] = []

    def dfs(n: str) -> None:
        state[n] = 1
        stack.append(n)
        for m in sorted(graph[n]):
            if state.get(m, 0) == 0:
                dfs(m)
            elif state.get(m) == 1:
                cyc = stack[stack.index(m):] + [m]
                if frozenset(cyc) not in seen_cycles:
                    seen_cycles.add(frozenset(cyc))
                    cycles.append(cyc)
        stack.pop()
        state[n] = 2

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            dfs(n)
    return cycles


def run(ctx: Context) -> list[Finding]:
    findings: list[Finding] = []
    edges: list[tuple[str, str, str, int]] = []
    calls_under: list[tuple[frozenset, str, bool, _ClassInfo, int]] = []
    infos: list[_ClassInfo] = []
    for f in ctx.parsed():
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(f, node)
                infos.append(info)
                _analyze_class(info, findings, edges, calls_under)

    # Cross-class order edges: a call made under lock A to a method named m
    # of ANY analyzed class adds A -> each lock m acquires.  Same-class
    # self-calls resolve exactly; foreign receivers resolve by method name
    # (conservative over-approximation — it can only ADD order edges).
    method_locks: dict[str, set[tuple[str, str]]] = {}  # name -> {(class, qual lock)}
    for info in infos:
        for m, locks in info.acquires.items():
            for lk in locks:
                method_locks.setdefault(m, set()).add((info.name, info.qual(lk)))
    for held_quals, callee, recv_self, info, lineno in calls_under:
        targets = method_locks.get(callee, set())
        if recv_self:
            targets = {(c, q) for c, q in targets if c == info.name}
        for _cls, q in sorted(targets):
            for h in sorted(held_quals):
                if h == q:
                    # Re-entry through a call: fatal only for plain Locks.
                    cls_name, lock_path = q.split(".", 1)
                    owner = next((i for i in infos if i.name == cls_name), None)
                    if owner is not None and owner.lock_kinds.get(lock_path) == "Lock" and recv_self:
                        findings.append(
                            Finding(
                                "THRD",
                                info.sf.rel,
                                lineno,
                                f"{info.name} calls {callee}() under plain Lock '{lock_path}' which {callee} re-acquires (deadlock)",
                            )
                        )
                    continue
                edges.append((h, q, info.sf.rel, lineno))

    edge_map: dict[tuple[str, str], tuple[str, int]] = {}
    for a, b, rel, lineno in edges:
        if a != b:
            edge_map.setdefault((a, b), (rel, lineno))
    for cyc in _find_cycles(edge_map):
        rel, lineno = edge_map[(cyc[0], cyc[1])]
        findings.append(
            Finding(
                "THRD",
                rel,
                lineno,
                "lock-acquisition-order cycle (potential deadlock): " + " -> ".join(cyc),
            )
        )
    return findings
