"""latency-smoke — the time-to-bind waterfall's standing gate (make check).

Two contracts, runnable standalone for a verdict (exit 0 = green), the
`make delta-smoke` pattern:

  1. COVERAGE — the steady-state scenario (seed 0) must pass its scorecard
     with the ``latency`` block green AND decompose at least 95% of its
     bound pods into waterfalls whose segments sum to TTB (a pod bound on
     the final cycle legitimately misses its confirm; anything beyond that
     tail is an instrumentation regression).
  2. SERVE — a live Scheduler's /debug/latency route must answer with the
     per-tier decomposition after a few real cycles (the daemon-side
     confirm-drain path, not the sim harness's reducer), and the per-pod
     /debug/pods waterfall block must be populated for a confirmed pod.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import json
import sys
import urllib.request

MIN_COVERAGE = 0.95


def main() -> int:
    import logging

    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.runtime.controller import Scheduler
    from tpu_scheduler.runtime.fake_api import FakeApiServer
    from tpu_scheduler.runtime.http_api import HttpApiServer
    from tpu_scheduler.sim.harness import run_scenario
    from tpu_scheduler.testing import make_node, make_pod
    from tpu_scheduler.utils.events import SEGMENTS

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    # 1. coverage: the scenario's pass gate REQUIRES the latency block ok.
    card = run_scenario("steady-state", seed=0)
    lat = card["latency"]
    print(
        f"steady-state: pass={card['pass']} measured={lat['measured']}/{card['pods']['bound_total']} "
        f"coverage={lat['coverage']} sum_ok={lat['sum_to_ttb_ok']} "
        f"cadence_wait_fraction={lat['cadence_wait_fraction']}"
    )
    if not card["pass"] or not lat["ok"]:
        print("FAIL: steady-state scorecard (latency block) is red", file=sys.stderr)
        return 1
    if lat["coverage"] is None or lat["coverage"] < MIN_COVERAGE:
        print(f"FAIL: waterfall coverage {lat['coverage']} under the {MIN_COVERAGE} bar", file=sys.stderr)
        return 1

    # 2. serve: a real controller + HTTP server; confirms drain on-cycle.
    api = FakeApiServer()
    for i in range(4):
        api.create_node(make_node(f"n{i}", cpu="8", memory="16Gi"))
    for i in range(12):
        api.create_pod(make_pod(f"p{i}", cpu="500m", memory="256Mi"))
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    server = HttpApiServer(api, recorder=sched.recorder, latency=lambda _r: sched.latency_snapshot()).start()
    try:
        for _ in range(3):  # bind cycle + confirm-drain cycle + margin
            sched.run_cycle()
        with urllib.request.urlopen(f"{server.base_url}/debug/latency", timeout=10) as resp:
            snap = json.loads(resp.read())
        tiers = snap.get("tiers", {})
        confirmed = snap.get("confirmed", 0)
        print(f"/debug/latency: confirmed={confirmed} tiers={sorted(tiers)}")
        if confirmed < 12 or "default" not in tiers:
            print("FAIL: /debug/latency missing confirmed pods", file=sys.stderr)
            return 1
        if set(tiers["default"]["segments_sum_s"]) != set(SEGMENTS):
            print("FAIL: /debug/latency segment taxonomy drifted", file=sys.stderr)
            return 1
        with urllib.request.urlopen(f"{server.base_url}/debug/pods/default/p0", timeout=10) as resp:
            pod = json.loads(resp.read())
        wf = pod.get("waterfall")
        if not wf or set(wf["segments"]) != set(SEGMENTS):
            print("FAIL: /debug/pods waterfall block missing or malformed", file=sys.stderr)
            return 1
    finally:
        server.stop()
        sched.close()
    print("latency-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
