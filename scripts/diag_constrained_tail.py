#!/usr/bin/env python
"""Composition of the constrained cycle's permanent-active tail.

Runs N rounds of the constrained flagship auction, then dissects the still-
active pods: who is blocked-everywhere-but-kept (positive-affinity hope),
who is a spread claimant, how much open spread quota exists vs how many
cells the claimants actually chose — the data that decides whether the tail
needs cheaper rounds, claimant spreading, or early termination.

Usage: python scripts/diag_constrained_tail.py [pods] [nodes] [warm_rounds]
"""
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes_n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    warm = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops import assign as A
    from tpu_scheduler.ops import constraints as C
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.masks import feasibility_block
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192)
    snap = synth_cluster(
        n_nodes=nodes_n, n_pending=pods, n_bound=2 * nodes_n, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    arrays = {k: jax.device_put(v) for k, v in packed.device_arrays().items()}
    nodes, ps = A.split_device_arrays(arrays)
    ps.update({k: jax.device_put(v) for k, v in cons.pod_arrays().items()})
    cmeta = {k: jax.device_put(v) for k, v in cons.meta_arrays().items()}
    cstate = {k: jax.device_put(v) for k, v in cons.state_arrays().items()}
    cstate = {**cstate, "stall": jnp.int32(0)}
    weights = jax.device_put(profile.weights())
    soft_spread, soft_pa, hard_pa = cons.n_spread_soft > 0, cons.n_ppa_terms > 0, cons.n_pa_terms > 0

    import functools

    @functools.partial(jax.jit, static_argnames=("block",))
    def prelude(nodes, ps, block):
        perm, out = A._prepare_pods(ps, block)
        return perm, out, nodes["node_avail"]

    body_fn = A._make_round_body(nodes, weights, profile.pod_block, False, False, cmeta, soft_spread, soft_pa, hard_pa)
    one_round = jax.jit(lambda s: body_fn(s))

    perm, ps, avail = prelude(nodes, ps, profile.pod_block)
    n_active = ps["active"].sum(dtype=jnp.int32)
    rounds = jnp.int32(0)
    state = (avail, ps, n_active, rounds, cstate)
    for _ in range(warm):
        state = one_round(state)
    avail, ps, n_active, rounds, cstate = state
    print(f"after {warm} rounds: active={int(n_active)}", flush=True)

    # --- dissect on host -------------------------------------------------
    h = {k: np.asarray(v) for k, v in ps.items()}
    hmeta = {k: np.asarray(v) for k, v in cmeta.items()}
    hstate = {k: np.asarray(v) for k, v in cstate.items() if k != "stall"}
    havail = np.asarray(avail)
    act = h["active"].astype(bool)
    na = act.sum()

    masks = C.round_blocked_masks(np, hstate, hmeta, soft_spread=soft_spread, soft_pa=soft_pa, hard_pa=hard_pa)
    hn = {k: np.asarray(v) for k, v in nodes.items()}
    m = feasibility_block(
        np, h["pod_req"], h["pod_sel"], h["pod_sel_count"], h["active"], havail,
        hn["node_labels"], hn["node_valid"], h["pod_ntol"], hn["node_taints"],
        h["pod_aff"], h["pod_has_aff"], hn["node_aff"],
    )
    blocked = C.blocked_block(np, h, masks)
    feas = m & ~blocked
    has = feas.any(axis=1)
    print(f"actives with a feasible node (claimants): {(act & has).sum()} / {na}")
    print(f"actives blocked everywhere (kept by pa_hope): {(act & ~has).sum()}")
    pa_declares = h["pod_pa_declares"].sum(axis=1) > 0
    sp_declares = h["pod_sp_declares"].sum(axis=1) > 0
    aa_carries = (h["pod_aa_carries"].sum(axis=1) > 0) | (h["pod_aa_matched"].sum(axis=1) > 0)
    print(f"  of blocked-everywhere: pa_declarers={(act & ~has & pa_declares).sum()}")
    print(f"  of claimants: sp_declarers={(act & has & sp_declares).sum()} pa={(act & has & pa_declares).sum()} aa={(act & has & aa_carries).sum()} plain={(act & has & ~sp_declares & ~pa_declares & ~aa_carries).sum()}")

    # Spread quota structure at this state
    uses_sp, skew, counts = hmeta["sp_uses_dom"], hmeta["sp_skew"], hstate["sp_counts"]
    lo = np.min(np.where(uses_sp > 0, counts, C.RANK_INF), axis=1)
    lo = np.where(lo >= C.RANK_INF, 0.0, lo)
    q = np.maximum(0.0, (skew + lo)[:, None] - counts) * uses_sp
    open_cells = (q >= 1.0).sum()
    print(f"spread: open (s,d) cells={open_cells}, total quota={q.sum():.0f}, constraints with any open cell={(q.max(axis=1) >= 1).sum()}/{int((uses_sp.sum(axis=1) > 0).sum())}")
    # Where do spread claimants actually point? Their best feasible node's cell.
    clam = act & has & sp_declares
    if clam.any():
        # crude: first feasible node per claimant (choose uses scores; this
        # approximates the chosen-cell spread structure)
        first_node = feas[clam].argmax(axis=1)
        ndc = hmeta["node_dom_c"]
        cell_hit = ndc[first_node]  # [C, D]
        decl = h["pod_sp_declares"][clam]  # [C, S]
        chosen_cells = set()
        for s in range(uses_sp.shape[0]):
            sel = decl[:, s] > 0
            if sel.any():
                doms = cell_hit[sel].argmax(axis=1)
                for d in np.unique(doms):
                    chosen_cells.add((s, int(d)))
        print(f"spread claimants' (first-feasible) distinct target cells: {len(chosen_cells)}")
    # Capacity left
    print(f"nodes with any remaining cpu: {(havail[:, 0] > 0).sum()}/{havail.shape[0]}; total cpu left={havail[:, 0].sum()}")


if __name__ == "__main__":
    main()
