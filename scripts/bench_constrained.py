#!/usr/bin/env python
"""Constrained-cycle driver comparison on the real chip.

The round-4 on-chip capture showed the constrained 50k x 5k row at 17 s /
64 rounds (cap) under the monolithic driver: a steep acceptance head, then a
long genuine-dependency tail of ~1-3 accepts per round — each tail round
still paying full padded-[P,S]/[P,T] constraint math (incl. the [S*P]
stable argsort in constraint_filter).  This experiment times monolithic vs
epochs (size-halving) drivers and prints the accepts-per-round profile that
motivates auto-selecting the driver for constrained cycles.

Usage: python scripts/bench_constrained.py [pods] [nodes]
"""
import os
import sys
import time
from collections import Counter
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 5_000
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(max_rounds=64)
    snap = synth_cluster(
        n_nodes=nodes, n_pending=pods, n_bound=2 * nodes, seed=7,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    print(f"shape: {packed.num_pods}x{len(packed.node_names)} padded {packed.padded_pods}x{packed.padded_nodes}", flush=True)
    print(f"vocab: T={cons.n_terms} Ta={cons.n_pa_terms} Tp={cons.n_ppa_terms} S={cons.n_spread} Ss={cons.n_spread_soft}", flush=True)
    print(f"padded: T={cons.pod_aa_carries.shape[1]} S={cons.pod_sp_declares.shape[1]} D={cons.node_dom_c.shape[1]}", flush=True)

    backend = TpuBackend()
    for driver in ("monolithic", "epochs"):
        prof = profile.with_(driver=driver)
        r = backend.schedule(packed, prof)  # warm/compile
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            r = backend.schedule(packed, prof)
            times.append(time.perf_counter() - t0)
        hist = Counter(int(x) for x in r.stats["acc_round"] if x >= 0)
        prof_str = " ".join(f"{k}:{hist[k]}" for k in sorted(hist))
        print(f"{driver}: {min(times):.3f}s  bound={len(r.bindings)}/{packed.num_pods} rounds={r.rounds}", flush=True)
        print(f"  accepts/round: {prof_str}", flush=True)
        unbound = packed.num_pods - len(r.bindings)
        print(f"  unbound: {unbound}", flush=True)


if __name__ == "__main__":
    main()
