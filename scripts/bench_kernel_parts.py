#!/usr/bin/env python
"""Bisect the choose kernel's per-pair cost: time stripped-down Pallas
variants (mask only, +matmuls, +score, +hash, +argmax) at the north-star
shape to find what eats the cycles."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

P, N = 106_496, 10_240
L = 8
BP, TN = 256, 2048

key = jax.random.PRNGKey(0)
req = jax.random.randint(key, (P, 2), 1, 1000, jnp.int32)
sel = (jax.random.uniform(key, (P, L)) < 0.2).astype(jnp.float32)
selc = sel.sum(-1, keepdims=True)
ranks = jnp.arange(P, dtype=jnp.uint32).reshape(-1, 1)
info = jnp.concatenate([jax.random.randint(key, (4, N), 500, 100000, jnp.int32), jnp.ones((1, N), jnp.int32), jnp.zeros((3, N), jnp.int32)], 0)
labels_t = (jax.random.uniform(key, (L, N)) < 0.5).astype(jnp.float32)


def make(variant):
    def kern(req_ref, sel_ref, selc_ref, ranks_ref, info_ref, labels_ref, out_ref, best_ref, bestidx_ref):
        j = pl.program_id(1)
        nb = pl.num_programs(1)
        tn = info_ref.shape[1]
        f32 = jnp.float32

        @pl.when(j == 0)
        def _():
            best_ref[:] = jnp.full_like(best_ref, float("-inf"))
            bestidx_ref[:] = jnp.zeros_like(bestidx_ref)

        avail = info_ref[0:2, :]
        alloc = info_ref[2:4, :]
        req_cpu = req_ref[:, 0:1]
        req_mem = req_ref[:, 1:2]
        fit = (req_cpu <= avail[0:1, :]) & (req_mem <= avail[1:2, :])
        sc = fit.astype(f32)
        if variant >= 1:  # + selector matmul
            counts = jnp.dot(sel_ref[:], labels_ref[:], preferred_element_type=f32)
            sc = sc + jnp.where(counts == selc_ref[:], f32(1.0), f32(0.0))
        if variant >= 2:  # + least-requested/balanced score (divisions)
            used_cpu = (alloc[0:1, :] - avail[0:1, :]) + req_cpu
            used_mem = (alloc[1:2, :] - avail[1:2, :]) + req_mem
            denom_cpu = jnp.maximum(alloc[0:1, :], 1).astype(f32)
            denom_mem = jnp.maximum(alloc[1:2, :], 1).astype(f32)
            frac_cpu = used_cpu.astype(f32) / denom_cpu
            frac_mem = used_mem.astype(f32) / denom_mem
            sc = sc + ((f32(1.0) - frac_cpu) + (f32(1.0) - frac_mem)) * f32(50.0)
            sc = sc + (f32(1.0) - jnp.abs(frac_cpu - frac_mem)) * f32(100.0)
        if variant >= 3:  # + jitter hash
            u32 = jnp.uint32
            node_idx = (j * tn + jax.lax.broadcasted_iota(jnp.int32, (1, tn), 1)).astype(u32)
            h = ranks_ref[:].astype(u32) * u32(2654435761) + node_idx * u32(2246822519)
            h = (h ^ (h >> u32(15))) & u32(0xFFFF)
            sc = sc + h.astype(jnp.int32).astype(f32) / f32(65536.0)
        # running argmax across node tiles
        tile_best = jnp.max(sc, axis=1, keepdims=True)
        tile_arg = jnp.argmax(sc, axis=1).reshape(-1, 1).astype(jnp.int32) + j * tn
        improve = tile_best > best_ref[:]
        bestidx_ref[:] = jnp.where(improve, tile_arg, bestidx_ref[:])
        best_ref[:] = jnp.where(improve, tile_best, best_ref[:])

        @pl.when(j == nb - 1)
        def _():
            out_ref[:] = bestidx_ref[:]

    @jax.jit
    def run():
        return pl.pallas_call(
            kern,
            grid=(P // BP, N // TN),
            in_specs=[
                pl.BlockSpec((BP, 2), lambda i, j: (i, 0)),
                pl.BlockSpec((BP, L), lambda i, j: (i, 0)),
                pl.BlockSpec((BP, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((BP, 1), lambda i, j: (i, 0)),
                pl.BlockSpec((8, TN), lambda i, j: (0, j)),
                pl.BlockSpec((L, TN), lambda i, j: (0, j)),
            ],
            out_specs=pl.BlockSpec((BP, 1), lambda i, j: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((P, 1), jnp.int32),
            scratch_shapes=[pltpu.VMEM((BP, 1), jnp.float32), pltpu.VMEM((BP, 1), jnp.int32)],
        )(req, sel, selc, ranks, info, labels_t)

    return run


names = ["fit+argmax", "+sel matmul", "+score divs", "+hash"]
for v in range(4):
    run = make(v)
    r = run()
    jax.block_until_ready(r)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(run())
        times.append(time.perf_counter() - t0)
    dt = min(times)
    print(f"variant {v} ({names[v]:12s}): {dt*1e3:6.1f} ms  ({P*N/dt/1e9:.2f} Gpair/s)", flush=True)
