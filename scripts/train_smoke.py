"""train-smoke — the policy-learning subsystem's standing gate (make check).

Three contracts, runnable standalone for a verdict (exit 0 = green), the
`make defrag-smoke` / `make delta-smoke` pattern:

  1. FLOOR — a tiny seeded CEM run (the ``train-smoke`` scenario, 3
     generations) must end with its best train objective >= the
     generation-0 default-profile objective.  The search injects the
     current mean as candidate 0 of every generation, so a violation
     means the evaluator itself went non-deterministic.
  2. REPRODUCIBLE — repeating the identical ``SearchConfig`` must
     reproduce the byte-identical generation history and chosen vector:
     one seed fully determines a training run.
  3. DISTILL ROUND-TRIP — the winning profile must survive the artifact
     round-trip (``to_file`` → ``from_file`` equality) and the artifact
     must re-evaluate to the SAME objective it was selected on — the
     zero-cost distillation contract at smoke scale.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile


def main() -> int:
    import logging

    from tpu_scheduler.learn.distill import distill, load_profile
    from tpu_scheduler.learn.env import ACTION_KNOBS
    from tpu_scheduler.learn.search import SearchConfig, episode_objective, train_profile

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    cfg = SearchConfig(
        scenarios=("train-smoke",),
        train_seeds=(0,),
        held_out_seeds=(101,),
        generations=3,
        population=6,
        seed=0,
    )
    a = train_profile(cfg)
    print(
        f"train-smoke: best train objective {a.train_objective} "
        f"(generation-0 default {a.default_train_objective}), improved={a.improved}, "
        f"held-out tuned={a.held_out} default={a.default_held_out}"
    )
    if a.train_objective < a.default_train_objective:
        print("FAIL: best objective fell below the generation-0 default-profile objective", file=sys.stderr)
        return 1

    b = train_profile(cfg)
    if json.dumps(a.history, sort_keys=True) != json.dumps(b.history, sort_keys=True) or a.vector != b.vector:
        print("FAIL: identical SearchConfig produced a different run — training is not seed-reproducible", file=sys.stderr)
        return 1
    print("train-smoke: history + chosen vector reproduce from the one seed")

    fd, path = tempfile.mkstemp(suffix=".json", prefix="train-smoke-")
    os.close(fd)
    try:
        distill(a, path)
        loaded = load_profile(path)
        if loaded != a.profile:
            print("FAIL: artifact round-trip changed the profile", file=sys.stderr)
            return 1
        vec = [float(getattr(loaded, name)) for name, _lo, _hi in ACTION_KNOBS]
        replayed = episode_objective(vec, "train-smoke", cfg.held_out_seeds[0])
        expected = a.held_out["train-smoke"]
        if replayed != expected:
            print(f"FAIL: distilled artifact re-evaluates to {replayed}, selection saw {expected}", file=sys.stderr)
            return 1
        print(f"train-smoke: distilled artifact re-evaluates to its selection objective ({replayed})")
    finally:
        os.unlink(path)

    print("train-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
