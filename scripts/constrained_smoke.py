"""constrained-smoke — the fused conflict filter's standing gate (make check).

Two contracts on a downscaled constrained cluster, runnable standalone for a
verdict (exit 0 = green), the `make sim-smoke` pattern:

  1. PARITY — the NumPy oracle and the jit engine must agree binding-for-
     binding (and accept-round-for-accept-round) on a constrained synth
     cluster: the active-set compaction, the fused segment scatter-min, the
     spread-domain projection, and the round-carried conflict state are all
     REQUIRED to be bitwise-neutral, and this is the cheap everyday check
     that they stayed so (tests/test_fuzz_parity.py is the thorough one).
  2. BUDGET — one warm constrained cycle at 2500×250 on the jit engine must
     finish in single-digit seconds.  Pre-fusion this shape measured ~60 s
     (ISSUE 9 / ROADMAP "constrained path at flagship scale"); post-fusion
     ~0.4 s on the dev box, so the 10 s bar holds ~20× of slow-CI margin
     while still failing hard if the filter ever re-grows a full-shape
     per-round sweep.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import sys
import time
from dataclasses import replace

import numpy as np

BUDGET_SECONDS = 10.0


def main() -> int:
    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192, max_rounds=64)
    kw = dict(
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )

    def packed_at(pods: int, nodes: int, seed: int):
        snap = synth_cluster(n_nodes=nodes, n_pending=pods, n_bound=2 * nodes, seed=seed, **kw)
        packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
        cons = pack_constraints(
            snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
            max_aa_terms=256, max_spread=256,
        )
        return replace(packed, constraints=cons)

    # 1. parity: oracle vs jit engine, bindings + rounds + accept rounds.
    tpu = TpuBackend()
    packed = packed_at(640, 64, seed=0)
    rn = NativeBackend().schedule(packed, profile)
    rt = tpu.schedule(packed, profile)
    ok_parity = (
        sorted(rn.bindings) == sorted(rt.bindings)
        and rn.rounds == rt.rounds
        and bool(np.array_equal(rn.stats["acc_round"], rt.stats["acc_round"]))
    )
    print(
        f"constrained-smoke parity 640x64: native=={len(rn.bindings)} bound/{rn.rounds} rounds, "
        f"jit=={len(rt.bindings)}/{rt.rounds} -> {'OK' if ok_parity else 'MISMATCH'}"
    )

    # 2. budget: one warm constrained cycle at the pre-fusion pathology shape.
    packed = packed_at(2500, 250, seed=0)
    tpu.schedule(packed, profile)  # warm/compile
    t0 = time.perf_counter()
    r = tpu.schedule(packed, profile)
    dt = time.perf_counter() - t0
    ok_budget = dt < BUDGET_SECONDS
    print(
        f"constrained-smoke budget 2500x250: {dt:.2f}s ({len(r.bindings)} bound, {r.rounds} rounds) "
        f"vs {BUDGET_SECONDS:.0f}s bar -> {'OK' if ok_budget else 'OVER BUDGET'}"
    )
    return 0 if (ok_parity and ok_budget) else 1


if __name__ == "__main__":
    sys.exit(main())
