#!/usr/bin/env python
"""On-chip self-test + tuning sweep for the fused choose kernel — run this
FIRST when the axon tunnel returns (the banded, constrained, and sharded
kernel variants have never met real Mosaic; the first-use strike guards
would downgrade silently and the bench would honestly report pallas:false).

Stages (each prints one PASS/FAIL line; exits nonzero on the first failure):
  1. plain kernel:      compiled-vs-jnp parity on a small synth cluster
  2. constrained kernel: same, full constraint mix
  3. full cycle:        TpuBackend.schedule with _pallas_proven asserted,
                        plain + constrained
  4. tile sweep:        flagship-shape choose timings across node_tile
                        {512, 1024, 2048} (pod_tile 256) — a TIMING probe;
                        any default change needs the on-chip parity check
                        first.  History: 1024 originally broke bit-parity
                        (Mosaic argmax tie-break, fixed 2026-07-31 with the
                        explicit lowest-index min-reduction) and is now the
                        measured-faster default; (512, 2048)+ historically
                        fails VMEM
  5. bench dry pass:    one reduced bench cycle (25k x 2.5k) end to end

Never kill this mid-run (SIGTERM during device init wedges the tunnel);
budget ~10 min after a cold compile cache.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def log(msg):
    print(msg, flush=True)


def main() -> int:
    import jax

    t0 = time.perf_counter()
    devices = jax.devices()
    platform = devices[0].platform
    log(f"devices ({time.perf_counter()-t0:.1f}s): {devices}")
    if platform != "tpu":
        log(f"FAIL: platform {platform!r} is not tpu — run under the axon tunnel")
        return 1

    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    from dataclasses import replace

    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.assign import assign_cycle, split_device_arrays
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"]

    # -- 1+2: assign_cycle parity, compiled pallas vs jnp ------------------
    def parity(constrained: bool) -> bool:
        kw = (
            dict(
                anti_affinity_fraction=0.2, spread_fraction=0.2, schedule_anyway_fraction=0.2,
                pod_affinity_fraction=0.15, preferred_pod_affinity_fraction=0.2,
            )
            if constrained
            else dict(tainted_fraction=0.3, node_affinity_fraction=0.2, soft_taint_fraction=0.2)
        )
        snap = synth_cluster(n_nodes=96, n_pending=512, n_bound=128, seed=3, **kw)
        packed = pack_snapshot(snap, pod_block=128, node_block=128)
        a = {k: jax.numpy.asarray(v) for k, v in packed.device_arrays().items()}
        nodes, pods = split_device_arrays(a)
        solve_kw = dict(max_rounds=32, block=256)
        if constrained:
            # Same raised budgets as bench.py: the synth vocabularies are
            # bounded but their distinct terms exceed the per-deployment-
            # sized defaults.
            cons = pack_constraints(
                snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
                max_aa_terms=256, max_spread=256,
            )
            pods.update({k: jax.numpy.asarray(v) for k, v in cons.pod_arrays().items()})
            solve_kw.update(
                cmeta={k: jax.numpy.asarray(v) for k, v in cons.meta_arrays().items()},
                cstate={k: jax.numpy.asarray(v) for k, v in cons.state_arrays().items()},
                soft_spread=cons.n_spread_soft > 0, soft_pa=cons.n_ppa_terms > 0, hard_pa=cons.n_pa_terms > 0,
            )
        weights = jax.numpy.asarray(profile.weights())
        base, *_ = assign_cycle(nodes, pods, weights, **solve_kw)
        pal, *_ = assign_cycle(nodes, pods, weights, use_pallas=True, **solve_kw)
        ok = bool((np.asarray(base) == np.asarray(pal)).all())
        log(f"{'PASS' if ok else 'FAIL'}: {'constrained' if constrained else 'plain'} kernel parity (compiled Mosaic vs jnp)")
        return ok

    if not parity(False):
        return 1
    if not parity(True):
        return 1

    # -- 2b: exact-tie lowest-index check, COMPILED on chip ----------------
    # Identical nodes + zero jitter weight: every feasible (pod, node)
    # score ties exactly across a whole node tile, so any non-lowest
    # Mosaic tie-break (the bug the min-reduction fixed) shifts choices
    # away from node 0.  The interpret-mode twin lives in
    # tests/test_pallas_choose.py; only THIS compiled run exercises the
    # real Mosaic lowering.
    from tpu_scheduler.core.snapshot import ClusterSnapshot
    from tpu_scheduler.models.profiles import SchedulingProfile
    from tpu_scheduler.testing import make_node, make_pod

    tie_nodes = [make_node(f"n{i:04d}", cpu="8", memory="16Gi") for i in range(1500)]
    tie_pods = [make_pod(f"p{i}", cpu="100m", memory="128Mi") for i in range(64)]
    tie_snap = ClusterSnapshot.build(tie_nodes, tie_pods)
    tie_packed = pack_snapshot(tie_snap, pod_block=128, node_block=128)
    ta = {k: jax.numpy.asarray(v) for k, v in tie_packed.device_arrays().items()}
    tn_nodes, tn_pods = split_device_arrays(ta)
    tie_w = jax.numpy.asarray(SchedulingProfile(spread_jitter=0.0).weights())
    tie_out, *_ = assign_cycle(tn_nodes, tn_pods, tie_w, max_rounds=1, block=256, use_pallas=True)
    tie_choice = np.asarray(tie_out)[: len(tie_pods)]
    ok = bool((tie_choice == 0).all())
    log(f"{'PASS' if ok else 'FAIL'}: compiled exact-tie lowest-index "
        f"(identical nodes, zero jitter -> every pod chooses node 0; got {sorted(set(tie_choice.tolist()))})")
    if not ok:
        return 1

    # -- 3: whole-backend proving ------------------------------------------
    for constrained in (False, True):
        kw = dict(anti_affinity_fraction=0.2, spread_fraction=0.2) if constrained else {}
        snap = synth_cluster(n_nodes=64, n_pending=256, n_bound=64, seed=5, **kw)
        packed = pack_snapshot(snap)
        if constrained:
            # Same raised budgets as bench.py: the synth vocabularies are
            # bounded but their distinct terms exceed the per-deployment-
            # sized defaults.
            cons = pack_constraints(
                snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
                max_aa_terms=256, max_spread=256,
            )
            packed = replace(packed, constraints=cons)
        b = TpuBackend()
        b.schedule(packed, profile)
        variant = constrained
        ok = variant in b._proven_variants and not b._disabled_variants
        log(f"{'PASS' if ok else 'FAIL'}: TpuBackend proving ({'constrained' if constrained else 'plain'}) "
            f"proven={sorted(b._proven_variants)} disabled={sorted(b._disabled_variants)}")
        if not ok:
            return 1

    # -- 4: tile sweep at flagship shape -----------------------------------
    from tpu_scheduler.ops.pallas_choose import build_node_info, choose_block_pallas

    snap = synth_cluster(n_nodes=10_000, n_pending=100_000, n_bound=20_000, seed=0)
    packed = pack_snapshot(snap, pod_block=8192, node_block=128)
    a = {k: jax.device_put(v) for k, v in packed.device_arrays().items()}
    info = build_node_info(a["node_avail"], a["node_alloc"], a["node_valid"])
    ranks = jax.numpy.arange(packed.padded_pods, dtype=jax.numpy.uint32)
    weights = jax.numpy.asarray(profile.weights())
    args = (
        a["pod_req"], a["pod_sel"], a["pod_sel_count"], a["pod_ntol"], a["pod_aff"], a["pod_has_aff"],
        a["pod_pref_w"], a["pod_ntol_soft"], a["pod_valid"], ranks, info,
        a["node_labels"].T, a["node_taints"].T, a["node_aff"].T, a["node_pref"].T, a["node_taints_soft"].T,
        weights,
    )
    pairs = packed.padded_pods * packed.padded_nodes
    best = None
    for node_tile in (512, 1024, 2048):
        try:
            c, _h = choose_block_pallas(*args, node_tile=node_tile)
            np.asarray(c)  # warm + sync (block_until_ready is unreliable here)
            t0 = time.perf_counter()
            c, _h = choose_block_pallas(*args, node_tile=node_tile)
            np.asarray(c)
            dt = time.perf_counter() - t0
            log(f"tile (256, {node_tile}): {dt*1e3:.1f} ms  ({pairs/dt/1e9:.1f} Gpair/s)")
            if best is None or dt < best[1]:
                best = (node_tile, dt)
        except Exception as e:  # noqa: BLE001 — a tile that fails VMEM is data, not a failure
            log(f"tile (256, {node_tile}): failed ({type(e).__name__}: {str(e)[:120]})")
    if best is None:
        log("FAIL: no node_tile compiled")
        return 1
    log(f"PASS: tile sweep — best node_tile {best[0]} at {best[1]*1e3:.1f} ms "
        f"(default is 1024, bit-exact since the explicit lowest-index tie-break "
        f"landed — Mosaic argmax is NOT first-index at every lane width; any "
        f"future tile change still needs the on-chip parity check first)")

    # -- 5: reduced bench pass (headline shape only — the constrained and
    # sharded evidence rows are the FULL bench's job) ----------------------
    import subprocess

    try:
        out = subprocess.run(
            [
                sys.executable, os.path.join(REPO_ROOT, "bench.py"),
                "--pods", "25000", "--nodes", "2500", "--repeats", "2",
                "--no-sharded-row", "--no-constrained-row",
            ],
            capture_output=True, text=True, timeout=1800, cwd=REPO_ROOT,
        )
    except subprocess.TimeoutExpired:
        log("FAIL: reduced bench exceeded 1800s (cold compile cache? tunnel degradation?)")
        return 1
    line = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
    log(f"bench (25k x 2.5k): {line}")
    ok = '"platform": "tpu"' in line and '"pallas": true' in line
    log(f"{'PASS' if ok else 'FAIL'}: reduced bench ran on tpu with the kernel live")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
