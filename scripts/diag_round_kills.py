#!/usr/bin/env python
"""Stage-by-stage kill attribution for ONE tail round of the constrained
flagship cycle: capture the device state after N rounds, then replay the
next round in numpy (the xp-generic expression tree is shared, so the
replay is bit-faithful) printing how many claimants each stage kills —
capacity prefix, AA conflict, PA bootstrap, spread dm-quota, spread dn.

Usage: python scripts/diag_round_kills.py [pods] [nodes] [warm_rounds]
"""
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes_n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    warm = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops import assign as A
    from tpu_scheduler.ops import constraints as C
    from tpu_scheduler.ops.masks import feasibility_block
    from tpu_scheduler.ops.pack import pack_snapshot, INT32_MAX
    from tpu_scheduler.ops.score import score_block
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192)
    snap = synth_cluster(
        n_nodes=nodes_n, n_pending=pods, n_bound=2 * nodes_n, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = C.pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    arrays = {k: jax.device_put(v) for k, v in packed.device_arrays().items()}
    nodes, ps = A.split_device_arrays(arrays)
    ps.update({k: jax.device_put(v) for k, v in cons.pod_arrays().items()})
    cmeta = {k: jax.device_put(v) for k, v in cons.meta_arrays().items()}
    cstate = {k: jax.device_put(v) for k, v in cons.state_arrays().items()}
    cstate = {**cstate, "stall": jnp.int32(0)}
    weights = jax.device_put(profile.weights())
    import functools

    @functools.partial(jax.jit, static_argnames=("block",))
    def prelude(nodes, ps, block):
        perm, out = A._prepare_pods(ps, block)
        return perm, out, nodes["node_avail"]

    body_fn = A._make_round_body(nodes, weights, profile.pod_block, False, False, cmeta, True, True, True)
    one_round = jax.jit(lambda s: body_fn(s))
    perm, ps, avail = prelude(nodes, ps, profile.pod_block)
    state = (avail, ps, ps["active"].sum(dtype=jnp.int32), jnp.int32(0), cstate)
    for _ in range(warm):
        state = one_round(state)
    avail, ps, n_active, rounds, cstate = state

    # Compaction keeps actives in a PREFIX of the pod arrays, so slicing to
    # the active count preserves array order (= rank order) and every
    # constraint-filter semantic while cutting the numpy replay ~6x.
    n_act = int(n_active)
    cut = max(1, n_act)
    h = {k: np.asarray(v)[:cut] for k, v in ps.items()}
    hn = {k: np.asarray(v) for k, v in nodes.items()}
    meta = {k: np.asarray(v) for k, v in cmeta.items()}
    st = {k: np.asarray(v) for k, v in cstate.items() if k != "stall"}
    havail = np.asarray(avail)
    w = np.asarray(weights)
    salt = int(rounds)
    n = havail.shape[0]
    act = h["active"].astype(bool)
    print(f"replaying round {salt}: active={act.sum()}", flush=True)

    masks = C.round_blocked_masks(np, st, meta, soft_spread=True, soft_pa=True, hard_pa=True)
    m = feasibility_block(
        np, h["pod_req"], h["pod_sel"], h["pod_sel_count"], h["active"], havail,
        hn["node_labels"], hn["node_valid"], h["pod_ntol"], hn["node_taints"],
        h["pod_aff"], h["pod_has_aff"], hn["node_aff"],
    )
    m = m & ~C.blocked_block(np, h, masks)
    node_idx = np.arange(n, dtype=np.uint32)
    sc = score_block(
        np, h["pod_req"], hn["node_alloc"], havail, w, h["ranks"], node_idx,
        pod_pref_w=h["pod_pref_w"], node_pref=hn["node_pref"],
        pod_ntol_soft=h["pod_ntol_soft"], node_taints_soft=hn["node_taints_soft"],
        pod_sps_declares=h["pod_sps_declares"], sp_penalty_node=masks["sp_penalty_node"],
        pod_ppa_w=h["pod_ppa_w"], ppa_cnt_node=masks["ppa_cnt_node"], salt=salt,
    )
    sc = np.where(m, sc, -np.inf)
    choice = sc.argmax(axis=1).astype(np.int32)
    has = m.any(axis=1)
    cand = act & has
    print(f"claimants (cand): {cand.sum()}", flush=True)

    # capacity prefix accept (replicating the segmented saturating scan)
    ch = np.where(cand, choice, n)
    order = np.argsort(ch, kind="stable")
    claim = np.where(cand[:, None], h["pod_req"], 0)
    accepted = np.zeros(len(ch), bool)
    avail_ext = np.concatenate([havail, np.zeros((1, havail.shape[1]), havail.dtype)])
    run = None
    prev_node = -1
    for idx in order:
        node = ch[idx]
        if node == n:
            break
        if node != prev_node:
            run = np.zeros(havail.shape[1], dtype=np.int64)
            prev_node = node
        run = np.minimum(run + claim[idx], INT32_MAX)
        if (run <= avail_ext[node]).all():
            accepted[idx] = True
        # NOTE: prefix semantics — once one fails, later same-node claimants
        # with smaller requests could still "fit" in the scan's saturating
        # prefix only if the running sum stays <= avail; replicate exactly:
        # the scan accepts iff the PREFIX SUM fits, so no reset on failure.
    cap_accepted = accepted.copy()
    print(f"capacity-accepted: {cap_accepted.sum()} (capacity-killed: {cand.sum() - cap_accepted.sum()})", flush=True)

    keep1 = C.constraint_filter(np, accepted, choice, h["ranks"], h, st, meta, hard_pa=True)
    print(f"after FULL constraint filter: {keep1.sum()}", flush=True)

    # Stage attribution: re-run pieces manually by toggling
    # (cheap trick: run filter with modified inputs)
    # AA-only: zero out spread + pa declarations
    h_aa = dict(h)
    h_aa["pod_sp_declares"] = np.zeros_like(h["pod_sp_declares"])
    h_aa["pod_pa_declares"] = np.zeros_like(h["pod_pa_declares"])
    keep_aa = C.constraint_filter(np, accepted, choice, h["ranks"], h_aa, st, meta, hard_pa=True)
    print(f"killed by AA conflicts: {accepted.sum() - keep_aa.sum()}", flush=True)
    h_sp = dict(h)
    h_sp["pod_aa_carries"] = np.zeros_like(h["pod_aa_carries"])
    h_sp["pod_aa_matched"] = np.zeros_like(h["pod_aa_matched"])
    h_sp["pod_pa_declares"] = np.zeros_like(h["pod_pa_declares"])
    keep_sp = C.constraint_filter(np, accepted, choice, h["ranks"], h_sp, st, meta, hard_pa=True)
    print(f"killed by spread quota: {accepted.sum() - keep_sp.sum()}", flush=True)

    # who are the survivors of capacity but killed overall?
    killed = cap_accepted & ~keep1
    sp_dec = h["pod_sp_declares"].sum(axis=1) > 0
    aa_m = h["pod_aa_matched"].sum(axis=1) > 0
    aa_c = h["pod_aa_carries"].sum(axis=1) > 0
    print(f"killed breakdown: total={killed.sum()} sp_declarer={np.sum(killed & sp_dec)} aa_matched={np.sum(killed & aa_m & ~sp_dec)} aa_carrier={np.sum(killed & aa_c & ~sp_dec)}", flush=True)
    # and the non-claimants: actives that had no feasible node
    print(f"actives with no feasible node this round: {np.sum(act & ~has)}", flush=True)


if __name__ == "__main__":
    main()
