"""protocol-smoke — the control plane's protocol-verification gate (make check).

Model-checks every committed ``# protocol:`` spec (the seven protocol
sites: circuit breaker, shard leases, gang reservations, drain executor,
provider lifecycle, placement ledger, fuzz plan lifecycle) against its
declared crash/retry environment and asserts:

  1. COVERAGE — at least ``MIN_MACHINES`` machines parse out of the tree
     (a deleted or broken contract fails here, not silently);
  2. SOUNDNESS — zero invariant/progress violations and zero spec parse
     errors (the PROT/MODL verdict, re-derived standalone);
  3. SIZE — every composite state space stays within
     ``MAX_MACHINE_STATES`` (exhaustive must stay cheap: a var-bound
     blowup fails the gate before it can eat the analyze budget) and
     explores more than one state (a vacuous machine proves nothing);
  4. BUDGET — parse + exhaustive exploration of ALL machines inside
     ``BUDGET_SECONDS`` of wall clock.

Off the tier-1 clock (milliseconds of wall); wired into `make check`.
"""

from __future__ import annotations

import sys
import time

BUDGET_SECONDS = 5.0
MIN_MACHINES = 7
MAX_MACHINE_STATES = 256


def main() -> int:
    from scripts.analyze import modelcheck, protocol
    from scripts.analyze.core import ROOT, Context, load_files

    t0 = time.perf_counter()
    files = load_files(["tpu_scheduler"])
    ctx = Context(files=files, root=ROOT, readme="")

    machines = []
    parse_errors = []
    for f in ctx.parsed():
        specs, errs = protocol.collect_machines(f)
        parse_errors.extend(errs)
        machines.extend(specs)

    ok = True
    if parse_errors:
        for e in parse_errors:
            print(f"FAIL: spec parse error — {e.render()}", file=sys.stderr)
        ok = False
    if len(machines) < MIN_MACHINES:
        print(
            f"FAIL: {len(machines)} protocol machines found, expected >= {MIN_MACHINES} "
            "(a protocol site lost its contract)",
            file=sys.stderr,
        )
        ok = False

    total_states = 0
    for spec, _cls in sorted(machines, key=lambda m: m[0].name):
        result = modelcheck.explore(spec)
        total_states += result["states"]
        props = len(spec.invariants) + len(spec.progress)
        print(
            f"{spec.name}: {result['states']} states, {result['transitions']} transitions, "
            f"{props} properties, {len(result['violations'])} violations  ({spec.rel})"
        )
        if result["capped"] or result["states"] > MAX_MACHINE_STATES:
            print(f"FAIL: {spec.name} state space exceeds {MAX_MACHINE_STATES}", file=sys.stderr)
            ok = False
        if result["states"] < 2:
            print(f"FAIL: {spec.name} explores {result['states']} state(s) — vacuous machine", file=sys.stderr)
            ok = False
        if props < 1:
            print(f"FAIL: {spec.name} declares no invariant/progress property", file=sys.stderr)
            ok = False
        for kind, name, trace, _line in result["violations"]:
            print(f"FAIL: {spec.name} {kind} '{name}' violated after: {' -> '.join(trace) or '(init)'}", file=sys.stderr)
            ok = False

    elapsed = time.perf_counter() - t0
    print(f"protocol-smoke: {len(machines)} machines, {total_states} composite states, {elapsed:.2f}s")
    if elapsed > BUDGET_SECONDS:
        print(f"FAIL: {elapsed:.2f}s > {BUDGET_SECONDS:.1f}s budget", file=sys.stderr)
        ok = False
    if ok:
        print("protocol-smoke: OK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
