"""defrag-smoke — the background rebalancer's standing gate (make check).

Two contracts, runnable standalone for a verdict (exit 0 = green), the
`make delta-smoke` / `make constrained-smoke` pattern:

  1. RECOVERY — the ``defrag-smoke`` scenario (seed 0) must pass its
     scorecard with the ``rebalance`` block green: final packing
     efficiency at or above the scenario gate, migrations within the
     budget, zero orphaned migrations, zero unbinds through an open
     breaker.
  2. BASELINE — the SAME scenario with the rebalancer forced OFF
     (``run_scenario(..., rebalance=False)``) must FAIL the same
     efficiency gate: if the baseline ever passes, the gate stopped
     measuring defragmentation and the scenario must be re-tuned.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import sys


def main() -> int:
    import logging

    from tpu_scheduler.sim.harness import run_scenario

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    card = run_scenario("defrag-smoke", seed=0)
    r = card["rebalance"]
    print(
        f"defrag-smoke ON: pass={card['pass']} efficiency={r['packing_efficiency']} "
        f"(gate {r['efficiency_gate']}) occupied={r['occupied_nodes']} migrations={r['migrations']}"
        f"/{r['migration_budget']} drained={r['nodes_drained']} orphaned={r['orphaned_migrations']}"
    )
    if not card["pass"] or not r["ok"]:
        print("FAIL: defrag-smoke scorecard (rebalance block) is red", file=sys.stderr)
        return 1
    if r["migrations"] == 0 or r["nodes_drained"] == 0:
        print("FAIL: the rebalancer did no work — the gate proved nothing", file=sys.stderr)
        return 1

    off = run_scenario("defrag-smoke", seed=0, rebalance=False)
    ro = off["rebalance"]
    print(
        f"defrag-smoke OFF: pass={off['pass']} efficiency={ro['packing_efficiency']} "
        f"(gate {ro['efficiency_gate']}) occupied={ro['occupied_nodes']}"
    )
    if off["pass"] or ro["ok"]:
        print(
            "FAIL: the rebalancer-off baseline passed the efficiency gate — the scenario no longer "
            "measures defragmentation",
            file=sys.stderr,
        )
        return 1
    if ro["packing_efficiency"] >= r["packing_efficiency"]:
        print("FAIL: rebalancing did not improve packing efficiency over the baseline", file=sys.stderr)
        return 1
    print("defrag-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
