#!/usr/bin/env python
"""Microbenchmark: one full-P choose (the auction's hot op) on the real chip,
jnp vs Pallas at several tile sizes.  Ground truth for kernel tuning."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from tpu_scheduler.models.profiles import PROFILES
from tpu_scheduler.ops.assign import split_device_arrays, _choose
from tpu_scheduler.ops.pack import pack_snapshot
from tpu_scheduler.testing import synth_cluster

P, N = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000, int(sys.argv[2]) if len(sys.argv) > 2 else 10_000

snap = synth_cluster(n_nodes=N, n_pending=P, n_bound=2 * N, seed=0)
packed = pack_snapshot(snap, pod_block=8192, node_block=128)
nodes, pods = split_device_arrays(packed.device_arrays())
prof = PROFILES["throughput"]
weights = jnp.asarray(prof.weights(), jnp.float32)

p = pods["pod_req"].shape[0]
ps = {k: v for k, v in pods.items() if k != "pod_prio"}
ps["ranks"] = jnp.arange(p, dtype=jnp.uint32)
ps["active"] = ps.pop("pod_valid")
avail = nodes["node_avail"]
n_active = jnp.int32(P)

BLOCK = 8192


def timeit(name, fn):
    r = fn()
    jax.block_until_ready(r)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = fn()
        jax.block_until_ready(r)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    pairs = p * avail.shape[0]
    print(f"{name}: {dt*1e3:.1f} ms  ({pairs/dt/1e9:.2f} Gpair/s)", flush=True)
    return r


@jax.jit
def jnp_choose(avail, ps, n_active):
    return _choose(avail, ps, n_active, nodes, weights, BLOCK, use_pallas=False)


c_j, h_j = timeit("jnp   block=8192", lambda: jnp_choose(avail, ps, n_active))

from tpu_scheduler.ops import pallas_choose as pc

for pt, nt in [(256, 512), (256, 2048), (512, 1024), (1024, 1024), (128, 4096), (512, 2048), (1024, 2048), (256, 8192)]:
    def pall(pt=pt, nt=nt):
        @jax.jit
        def f(avail, ps, n_active):
            info = pc.build_node_info(avail, nodes["node_alloc"], nodes["node_valid"])
            lt, tt = nodes["node_labels"].T, nodes["node_taints"].T
            at, prt, tst = nodes["node_aff"].T, nodes["node_pref"].T, nodes["node_taints_soft"].T
            outc = jnp.zeros((p,), jnp.int32)
            outh = jnp.zeros((p,), bool)
            for lo in range(0, p, BLOCK):
                blk = {k: ps[k][lo : lo + BLOCK] for k in ps}
                c, h = pc.choose_block_pallas(
                    blk["pod_req"], blk["pod_sel"], blk["pod_sel_count"], blk["pod_ntol"],
                    blk["pod_aff"], blk["pod_has_aff"], blk["pod_pref_w"], blk["pod_ntol_soft"],
                    blk["active"], blk["ranks"], info, lt, tt, at, prt, tst, weights,
                    salt=jnp.int32(0), pod_tile=pt, node_tile=nt,
                )
                outc = outc.at[lo : lo + BLOCK].set(c)
                outh = outh.at[lo : lo + BLOCK].set(h)
            return outc, outh
        return f

    try:
        f = pall()
        c_p, h_p = timeit(f"pallas pt={pt:4d} nt={nt:4d}", lambda: f(avail, ps, n_active))
    except Exception as e:  # noqa: BLE001
        print(f"pallas pt={pt} nt={nt}: FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)
