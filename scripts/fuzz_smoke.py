"""fuzz-smoke — the chaos fuzzer's standalone gate (make check).

Runs a small pinned campaign (``PLANS`` seeded plans from seed 0) through
the invariant oracle plus a full corpus replay, and asserts:

  1. CORPUS — every checked-in reproducer in ``tests/fuzz_corpus/``
     replays bit-identically (fingerprint, verdict, violations, pins);
  2. GREEN — the pinned campaign finds zero violations (a finding here is
     a real regression: the exact plan JSON is printed for shrinking);
  3. COVERAGE — the campaign reaches at least ``MIN_PAIRS`` distinct
     (fault-op × state-facet) pairs including ``MIN_LEASE_PAIRS`` lease
     pairs (a collapsed generator or dead facet sampler fails here, not
     silently);
  4. BUDGET — campaign + replay inside ``BUDGET_SECONDS`` of wall clock.

Off the tier-1 clock (a few seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import os
import sys
import time

BUDGET_SECONDS = 30.0
PLANS = 24
MIN_PAIRS = 30
MIN_LEASE_PAIRS = 4
CORPUS_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests", "fuzz_corpus")


def main() -> int:
    from tpu_scheduler.sim.fuzz import CoverageMap, PlanGenerator, run_plan
    from tpu_scheduler.sim.fuzz.corpus import load_corpus, replay_entry
    from tpu_scheduler.utils.tracing import configure_logging

    configure_logging("ERROR")
    t0 = time.perf_counter()
    ok = True

    entries = load_corpus(CORPUS_DIR)
    if not entries:
        print(f"FAIL: no corpus entries under {CORPUS_DIR}", file=sys.stderr)
        ok = False
    for entry in entries:
        good, problems, _card = replay_entry(entry)
        print(f"corpus {entry['name']}: {'ok' if good else 'DRIFTED'} ({len(entry['plan'].ops)} ops)")
        if not good:
            for p in problems:
                print(f"FAIL: corpus {entry['name']}: {p}", file=sys.stderr)
            ok = False

    coverage = CoverageMap()
    gen = PlanGenerator(seed=0, coverage=coverage)
    violations_found = 0
    for i in range(PLANS):
        plan = gen.next_plan(i)
        _card, violations = run_plan(plan, seed=0, coverage=coverage)
        if violations:
            violations_found += 1
            from tpu_scheduler.sim.fuzz import plan_to_json

            print(
                f"FAIL: {plan.plan_id} violated {violations} — shrink with "
                f"`python -m tpu_scheduler.sim.cli fuzz`; plan: {plan_to_json(plan)}",
                file=sys.stderr,
            )
    if violations_found:
        ok = False

    pairs = coverage.distinct()
    lease = coverage.lease_pairs()
    if pairs < MIN_PAIRS:
        print(f"FAIL: {pairs} coverage pairs < {MIN_PAIRS} floor", file=sys.stderr)
        ok = False
    if lease < MIN_LEASE_PAIRS:
        print(f"FAIL: {lease} lease coverage pairs < {MIN_LEASE_PAIRS} floor", file=sys.stderr)
        ok = False

    elapsed = time.perf_counter() - t0
    print(
        f"fuzz-smoke: {len(entries)} corpus entries, {PLANS} plans, "
        f"{pairs} coverage pairs ({lease} lease), {violations_found} violations, {elapsed:.2f}s"
    )
    if elapsed > BUDGET_SECONDS:
        print(f"FAIL: {elapsed:.2f}s > {BUDGET_SECONDS:.1f}s budget", file=sys.stderr)
        ok = False
    if ok:
        print("fuzz-smoke: OK")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
