#!/usr/bin/env python
"""Zero-dependency lint gate — the error classes a round-2 regression shipped
with (dead exports, stale imports) plus basic hygiene, implemented on the
stdlib so the gate runs in the build image (which carries no linter).

Checks (all hard failures) — the whole lint policy lives HERE; every rule
named in pyproject.toml executes on every `make check` (no config for
linters the image cannot run):
  F401  imported name never used in the module (``__init__.py`` re-exports
        listed in ``__all__`` are exempt)
  F822  ``__all__`` names a symbol the module does not define
  F841  local variable assigned once and never read (conservative: plain
        name targets only; ``_``-prefixed and tuple-unpacked names exempt —
        unpacking documents structure)
  E711  comparison to None with ==/!= (use is / is not)
  E712  comparison to True/False with ==/!= (use the value or is)
  B006  mutable default argument (list/dict/set literal or call)
  DEAD  a non-underscore symbol in a module's ``__all__`` that no other file
        in the package, tests, bench, or entry scripts references (the
        round-2 'three dead soft scorers' class)
  METR  a ``scheduler_*`` metric-name literal used anywhere in the package
        that does not appear in the README metric catalogue — the docs
        drift gate for the Observability section (a metric added without
        cataloguing it would otherwise rot the docs silently)
  SIMC  simulator catalogue drift (same pattern as METR, for the
        "Simulation & chaos" README section): every registered scenario
        name (``Scenario(name=...)`` in sim/scenarios.py), every chaos knob
        (``ChaosConfig``/``ChaosWindow`` dataclass field), and every
        scorecard top-level field (``SCORECARD_FIELDS``) must appear in
        README.md
  W291  trailing whitespace / W191 tabs in indentation
  E999  syntax errors (via ast.parse)

Usage: python scripts/lint.py [paths...]   (defaults to the package + tests)
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DEFAULT_PATHS = ["tpu_scheduler", "tests", "bench.py", "__graft_entry__.py", "scripts"]


def iter_py(paths: list[str]) -> list[pathlib.Path]:
    out = []
    for p in paths:
        path = ROOT / p
        if path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            out.append(path)
    return out


class ImportUsage(ast.NodeVisitor):
    """Collect imported names and every name/attribute usage."""

    def __init__(self):
        self.imports: dict[str, int] = {}  # bound name -> lineno
        self.used: set[str] = set()

    def visit_Import(self, node):
        for a in node.names:
            name = a.asname or a.name.split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return  # future imports act by existing, never by reference
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def module_all(tree: ast.Module) -> list[str]:
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and isinstance(node.value, (ast.List, ast.Tuple)):
                    return [e.value for e in node.value.elts if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []


def top_level_defs(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Tuple):
                    names.update(e.id for e in t.elts if isinstance(e, ast.Name))
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            names.add(node.target.id)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                if a.name != "*":
                    names.add(a.asname or a.name.split(".")[0])
    return names


class FunctionScopeChecks(ast.NodeVisitor):
    """Per-function rules: F841 unused locals, B006 mutable defaults."""

    def __init__(self, relpath: str, errors: list[str]):
        self.relpath = relpath
        self.errors = errors

    def _check_function(self, node):
        # B006 — mutable literals/constructors as parameter defaults.
        for default in list(node.args.defaults) + [d for d in node.args.kw_defaults if d is not None]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                self.errors.append(f"{self.relpath}:{default.lineno}: B006 mutable default argument")
        # F841 — plain-name single assignments never read in the function.
        # STORES are collected from this function's OWN scope only (nested
        # function bodies get their own visit — walking them here would
        # double-report their dead stores against the outer scope); READS
        # come from the full walk so a closure's use of an outer local still
        # counts (conservative: an inner local shadowing an outer name can
        # mask an outer dead store — false negatives over false positives).
        def own_scope(n):
            for child in ast.iter_child_nodes(n):
                # Nested functions/lambdas AND class bodies are their own
                # scopes — a class attribute is not a function local (it is
                # read via ast.Attribute, which never registers as a Name
                # Load, so walking it would hard-fail valid code).
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
                    continue
                yield child
                yield from own_scope(child)

        assigned: dict[str, int] = {}
        read: set[str] = set()
        exempt: set[str] = set()
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, ast.AugAssign) and isinstance(sub.target, ast.Name):
                # x += v mutates x in place — a use, not a dead store (the
                # ledger-accumulator pattern).
                read.add(sub.target.id)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                read.add(sub.id)
        for sub in own_scope(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                assigned.setdefault(sub.id, sub.lineno)
            # global/nonlocal writes are module/outer-scope effects, and
            # loop induction variables are iteration plumbing (ruff would
            # file them under B007) — neither is an unused LOCAL.
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                exempt.update(sub.names)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                exempt.update(n.id for n in ast.walk(sub.target) if isinstance(n, ast.Name))
            elif isinstance(sub, ast.comprehension):
                exempt.update(n.id for n in ast.walk(sub.target) if isinstance(n, ast.Name))
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                # `with ... as x:` targets are context handles pyflakes/ruff
                # never file under F841 (e.g. pytest.raises(...) as exc).
                for item in sub.items:
                    if item.optional_vars is not None:
                        exempt.update(n.id for n in ast.walk(item.optional_vars) if isinstance(n, ast.Name))
            elif isinstance(sub, ast.Assign):
                # Tuple-unpack targets document structure — exempt them.
                for t in sub.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        exempt.update(n.id for n in ast.walk(t) if isinstance(n, ast.Name))
        args = {a.arg for a in node.args.args + node.args.kwonlyargs + node.args.posonlyargs}
        for name, lineno in sorted(assigned.items(), key=lambda kv: kv[1]):
            if name in read or name in exempt or name in args or name.startswith("_"):
                continue
            if name in ("self", "cls"):
                continue
            self.errors.append(f"{self.relpath}:{lineno}: F841 local variable '{name}' assigned but never used")

    def visit_FunctionDef(self, node):
        self._check_function(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def comparison_checks(tree: ast.Module, relpath: str, errors: list[str]) -> None:
    """E711 (== None) / E712 (== True/False) — either side of the ==."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        # Operand i of op i is left for i == 0, else comparators[i-1]; check
        # both sides so Yoda comparisons (None == x) are caught too.
        operands = [node.left] + list(node.comparators)
        for i, op in enumerate(node.ops):
            if not isinstance(op, (ast.Eq, ast.NotEq)):
                continue
            for side in (operands[i], operands[i + 1]):
                if not isinstance(side, ast.Constant):
                    continue
                if side.value is None:
                    errors.append(f"{relpath}:{node.lineno}: E711 comparison to None (use 'is'/'is not')")
                elif side.value is True or side.value is False:
                    errors.append(f"{relpath}:{node.lineno}: E712 comparison to {side.value} (use the value or 'is')")


def main(argv: list[str]) -> int:
    files = iter_py(argv or DEFAULT_PATHS)
    errors: list[str] = []
    sources: dict[pathlib.Path, str] = {}
    trees: dict[pathlib.Path, ast.Module] = {}

    for f in files:
        text = f.read_text()
        sources[f] = text
        try:
            trees[f] = ast.parse(text, filename=str(f))
        except SyntaxError as e:
            errors.append(f"{f.relative_to(ROOT)}:{e.lineno}: E999 syntax error: {e.msg}")
            continue
        for i, line in enumerate(text.splitlines(), 1):
            if line != line.rstrip():
                errors.append(f"{f.relative_to(ROOT)}:{i}: W291 trailing whitespace")
            if line.startswith("\t"):
                errors.append(f"{f.relative_to(ROOT)}:{i}: W191 tab in indentation")

    # F401 / F822 per module
    for f, tree in trees.items():
        exported = set(module_all(tree))
        usage = ImportUsage()
        usage.visit(tree)
        # Names referenced in string annotations / docstring doctests are out
        # of scope; __init__ re-exports are legitimate when listed in __all__.
        is_init = f.name == "__init__.py"
        src = sources[f]
        for name, lineno in usage.imports.items():
            if name in usage.used or name == "_":
                continue
            if is_init or name in exported:
                continue
            # A conservative text check catches usage forms the AST visitor
            # does not model (e.g. inside f-string format specs).
            if len(re.findall(rf"\b{re.escape(name)}\b", src)) > 1:
                continue
            errors.append(f"{f.relative_to(ROOT)}:{lineno}: F401 '{name}' imported but unused")
        defined = top_level_defs(tree)
        for name in exported:
            if name not in defined:
                errors.append(f"{f.relative_to(ROOT)}:1: F822 undefined name '{name}' in __all__")
        relpath = str(f.relative_to(ROOT))
        FunctionScopeChecks(relpath, errors).visit(tree)
        comparison_checks(tree, relpath, errors)

    # DEAD: exported but referenced nowhere else in the repo
    pkg_files = [f for f in files if f.suffix == ".py"]
    all_text = {f: sources[f] for f in pkg_files if f in sources}
    for f, tree in trees.items():
        if "tpu_scheduler" not in str(f) or f.name == "__init__.py":
            continue
        for name in module_all(tree):
            refs = 0
            for g, text in all_text.items():
                hits = len(re.findall(rf"\b{re.escape(name)}\b", text))
                if g == f:
                    # definition + __all__ entry account for 2 mentions
                    refs += max(0, hits - 2)
                else:
                    refs += hits
            if refs == 0:
                errors.append(f"{f.relative_to(ROOT)}:1: DEAD export '{name}' is referenced nowhere")

    # METR: every scheduler_* metric name used in the package must be
    # catalogued in the README Observability section.
    metric_re = re.compile(r'"(scheduler_[a-z0-9_]+)"')
    readme = (ROOT / "README.md").read_text() if (ROOT / "README.md").exists() else ""
    metric_names: set[str] = set()
    for f, text in sources.items():
        rel = f.relative_to(ROOT)
        if rel.parts[:1] == ("tpu_scheduler",):
            metric_names.update(metric_re.findall(text))
    for name in sorted(metric_names):
        if name not in readme:
            errors.append(
                f"README.md:1: METR metric '{name}' is used in tpu_scheduler/ but missing from the README metric catalogue"
            )

    # SIMC: the simulator's scenario registry, chaos knobs, and scorecard
    # schema must be catalogued in the README "Simulation & chaos" section.
    sim_catalogue: list[tuple[str, str]] = []  # (kind, name)
    for f, tree in trees.items():
        rel = f.relative_to(ROOT)
        if rel.parts[:2] != ("tpu_scheduler", "sim"):
            continue
        if f.name == "scenarios.py":
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Scenario"
                ):
                    for kw in node.keywords:
                        if kw.arg == "name" and isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                            sim_catalogue.append(("scenario", kw.value.value))
        elif f.name == "chaos.py":
            for node in tree.body:
                if isinstance(node, ast.ClassDef) and node.name in ("ChaosConfig", "ChaosWindow"):
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                            sim_catalogue.append(("chaos knob", stmt.target.id))
        elif f.name == "scorecard.py":
            for node in tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and t.id == "SCORECARD_FIELDS" and isinstance(node.value, (ast.Tuple, ast.List)):
                            for e in node.value.elts:
                                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                                    sim_catalogue.append(("scorecard field", e.value))
    for kind, name in sorted(set(sim_catalogue)):
        if name not in readme:
            errors.append(
                f"README.md:1: SIMC {kind} '{name}' exists in tpu_scheduler/sim/ but is missing from the README \"Simulation & chaos\" catalogue"
            )

    for e in sorted(errors):
        print(e)
    print(f"lint: {len(files)} files, {len(errors)} errors")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
