#!/usr/bin/env python
"""Back-compat shim — the lint gate moved to the ``scripts/analyze``
package (single-parse driver, pluggable passes, baseline gate).

Every rule the monolithic lint.py enforced (F401/F822/F841/E711/E712/B006/
DEAD/METR/SIMC/W291/W191/E999) was ported as a pass, joined by the
repo-invariant analyzers THRD (lock discipline), JAXP (jit purity), DTRM
(sim determinism), SHPE (shape/dtype contracts), and EXCP (failure-class
taxonomy closure).  This shim execs the new driver with identical CLI
semantics, so ``python scripts/lint.py [paths...]`` and the pre-commit
hook (which passes ``--changed-only`` for the git-scoped fast path) keep
working unchanged.  Prefer ``python -m scripts.analyze`` (it adds
``--rule``, ``--json``, ``--json-out``, ``--budget``, ``--list-rules``).
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from scripts.analyze.driver import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
