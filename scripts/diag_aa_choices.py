#!/usr/bin/env python
"""Why don't the tail's AA claimants spread?  At the round-20 state, compute
the actual choose (score+jitter+mask argmax) for the claimants of a few AA
terms and report: distinct chosen nodes, the score landscape's width (#nodes
within jitter amplitude of each pod's top), and the capacity-accept +
AA-filter outcome — pinpointing which stage serializes the tail.

Usage: python scripts/diag_aa_choices.py [pods] [nodes] [warm_rounds]
"""
import os
import sys
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes_n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    warm = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops import assign as A
    from tpu_scheduler.ops import constraints as C
    from tpu_scheduler.ops.masks import feasibility_block
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.ops.score import score_block
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192)
    snap = synth_cluster(
        n_nodes=nodes_n, n_pending=pods, n_bound=2 * nodes_n, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = C.pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    arrays = {k: jax.device_put(v) for k, v in packed.device_arrays().items()}
    nodes, ps = A.split_device_arrays(arrays)
    ps.update({k: jax.device_put(v) for k, v in cons.pod_arrays().items()})
    cmeta = {k: jax.device_put(v) for k, v in cons.meta_arrays().items()}
    cstate = {k: jax.device_put(v) for k, v in cons.state_arrays().items()}
    cstate = {**cstate, "stall": jnp.int32(0)}
    weights = jax.device_put(profile.weights())
    import functools

    @functools.partial(jax.jit, static_argnames=("block",))
    def prelude(nodes, ps, block):
        perm, out = A._prepare_pods(ps, block)
        return perm, out, nodes["node_avail"]

    body_fn = A._make_round_body(nodes, weights, profile.pod_block, False, False, cmeta, True, True, True)
    one_round = jax.jit(lambda s: body_fn(s))
    perm, ps, avail = prelude(nodes, ps, profile.pod_block)
    state = (avail, ps, ps["active"].sum(dtype=jnp.int32), jnp.int32(0), cstate)
    for _ in range(warm):
        state = one_round(state)
    avail, ps, n_active, rounds, cstate = state

    h = {k: np.asarray(v) for k, v in ps.items()}
    hn = {k: np.asarray(v) for k, v in nodes.items()}
    hmeta = {k: np.asarray(v) for k, v in cmeta.items()}
    hstate = {k: np.asarray(v) for k, v in cstate.items() if k != "stall"}
    havail = np.asarray(avail)
    w = np.asarray(weights)
    act = h["active"].astype(bool)

    masks = C.round_blocked_masks(np, hstate, hmeta, soft_spread=True, soft_pa=True, hard_pa=True)
    m = feasibility_block(
        np, h["pod_req"], h["pod_sel"], h["pod_sel_count"], h["active"], havail,
        hn["node_labels"], hn["node_valid"], h["pod_ntol"], hn["node_taints"],
        h["pod_aff"], h["pod_has_aff"], hn["node_aff"],
    )
    feas = m & ~C.blocked_block(np, h, masks)
    has = feas.any(axis=1)

    # Pick the 3 largest AA terms among active claimants
    carr = h["pod_aa_carries"][act & has]
    sizes = carr.sum(axis=0)
    top_terms = np.argsort(-sizes)[:3]
    node_idx = np.arange(havail.shape[0], dtype=np.uint32)
    for t in top_terms:
        sel = act & has & (h["pod_aa_carries"][:, t] > 0)
        cnt = sel.sum()
        if cnt == 0:
            continue
        rows = np.flatnonzero(sel)[:2000]
        sc = score_block(
            np, h["pod_req"][rows], hn["node_alloc"], havail, w, h["ranks"][rows], node_idx,
            pod_pref_w=h["pod_pref_w"][rows], node_pref=hn["node_pref"],
            pod_ntol_soft=h["pod_ntol_soft"][rows], node_taints_soft=hn["node_taints_soft"],
            pod_sps_declares=h["pod_sps_declares"][rows], sp_penalty_node=masks["sp_penalty_node"],
            pod_ppa_w=h["pod_ppa_w"][rows], ppa_cnt_node=masks["ppa_cnt_node"],
            salt=int(rounds),
        )
        sc = np.where(feas[rows], sc, -np.inf)
        choice = sc.argmax(axis=1)
        distinct = len(set(choice.tolist()))
        feas_counts = feas[rows].sum(axis=1)
        top = sc.max(axis=1)
        # width: nodes within the 32-point jitter amplitude of this pod's top
        width = (sc >= (top[:, None] - 32.0)).sum(axis=1)
        print(
            f"term {t}: claimants={cnt} distinct_choice={distinct} "
            f"feasible/pod med={np.median(feas_counts):.0f} "
            f"nodes-within-32pts med={np.median(width):.0f} min={width.min()} max={width.max()}",
            flush=True,
        )


if __name__ == "__main__":
    main()
