"""delta-smoke — the incremental engine's standing gate (make check).

Two contracts, runnable standalone for a verdict (exit 0 = green), the
`make sim-smoke` / `make constrained-smoke` pattern:

  1. PARITY — the churn-steady-state scenario (seed 0) must pass its
     scorecard with the ``incremental`` block green: full_solve_fraction
     <= 0.10 (the delta cycle IS the default) and zero shadow-solve
     mismatches across every sampled cycle (the full-wave solve, run
     beside the delta path, placed exactly the same pod set each time).
  2. BUDGET — on a downscaled synthetic cluster (2000×200, ~1% churn per
     cycle) the warm delta cycle must run at least 3× faster than the cold
     full-wave cycle and under an absolute 1 s bar.  The dev box measures
     ~10 ms delta vs ~1 s cold; the relative bound keeps slow-CI margin
     while still failing hard if the delta path ever re-grows an
     O(all-pods) sweep.

Off the tier-1 clock (seconds of wall); wired into `make check`.
"""

from __future__ import annotations

import statistics
import sys
import time

BUDGET_SECONDS = 1.0
MIN_SPEEDUP = 3.0


def main() -> int:
    import logging

    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.runtime.controller import Scheduler
    from tpu_scheduler.runtime.fake_api import FakeApiServer
    from tpu_scheduler.sim.harness import run_scenario
    from tpu_scheduler.testing import synth_cluster

    logging.getLogger("tpu_scheduler").setLevel(logging.WARNING)

    # 1. parity: the scenario's pass gate REQUIRES the incremental block ok.
    card = run_scenario("churn-steady-state", seed=0)
    inc = card["incremental"]
    print(
        f"churn-steady-state: pass={card['pass']} delta={inc['delta_cycles']} "
        f"full={inc['full_solves']} fraction={inc['full_solve_fraction']} "
        f"shadow={inc['shadow_checks']}/{inc['shadow_mismatches']} mismatches"
    )
    if not card["pass"] or not inc["ok"]:
        print("FAIL: churn-steady-state scorecard (incremental block) is red", file=sys.stderr)
        return 1
    if inc["shadow_checks"] < 1:
        print("FAIL: no shadow-solve parity checks ran", file=sys.stderr)
        return 1

    # 2. budget: warm delta cycles must beat the cold full wave by >= 3x.
    from dataclasses import replace as dc_replace

    base = synth_cluster(n_nodes=200, n_pending=2000, n_bound=400, seed=0)
    api = FakeApiServer()
    api.load(base.nodes, base.pods)
    sched = Scheduler(api, NativeBackend(), requeue_seconds=0.0)
    t0 = time.perf_counter()
    sched.run_cycle()
    cold = time.perf_counter() - t0
    wave = synth_cluster(n_nodes=200, n_pending=2000, n_bound=0, seed=1).pending_pods()
    bound_pool = [p for p in base.pods if p.spec is not None and p.spec.node_name is None]
    churn, prev, walls = 20, [], []
    for w in range(5):
        for p in prev:
            api.delete_pod(p.metadata.namespace or "default", p.metadata.name)
        for p in bound_pool[w * churn : (w + 1) * churn]:
            api.delete_pod(p.metadata.namespace or "default", p.metadata.name)
        prev = [
            dc_replace(p, metadata=dc_replace(p.metadata, name=f"s{w}-{p.metadata.name}"))
            for p in wave[:churn]
        ]
        for p in prev:
            api.create_pod(p)
        t0 = time.perf_counter()
        sched.run_cycle()
        walls.append(time.perf_counter() - t0)
    warm = statistics.median(walls)
    stats = sched.delta.stats()
    print(
        f"budget: cold full wave {cold:.3f}s, warm delta median {warm:.4f}s "
        f"(x{cold / warm:.1f}), delta cycles {stats['delta_cycles']}"
    )
    if stats["delta_cycles"] < 5:
        print("FAIL: churn cycles did not ride the delta path", file=sys.stderr)
        return 1
    if warm > BUDGET_SECONDS:
        print(f"FAIL: warm delta cycle {warm:.3f}s over the {BUDGET_SECONDS:.1f}s budget", file=sys.stderr)
        return 1
    if cold / warm < MIN_SPEEDUP:
        print(f"FAIL: delta speedup x{cold / warm:.1f} under the x{MIN_SPEEDUP:.0f} bar", file=sys.stderr)
        return 1
    print("delta-smoke green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
