#!/usr/bin/env python
"""Decisive layout experiment: the choose kernel with pod features packed
into ONE wide [P, 64] f32 operand (+ one [P, 8] i32), passed as jit
ARGUMENTS.  If this runs ~50ms where the narrow-operand version runs
~260ms, the narrow-array relayout is confirmed as the bottleneck."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

P, N = 106_496, 10_240
BP, TN = 256, 2048
F = 64  # wide f32 feature width; cols: sel 0:8, ntol 8:16, aff 16:24, prefw 24:32, ntols 32:40, selc 40, hasaff 41

key = jax.random.PRNGKey(0)
pod_f32 = jnp.zeros((P, F), jnp.float32)
sel = (jax.random.uniform(key, (P, 8)) < 0.2).astype(jnp.float32)
pod_f32 = pod_f32.at[:, 0:8].set(sel).at[:, 40].set(sel.sum(-1))
pod_i32 = jnp.zeros((P, 8), jnp.int32)
pod_i32 = pod_i32.at[:, 0:2].set(jax.random.randint(key, (P, 2), 1, 1000, jnp.int32))
pod_i32 = pod_i32.at[:, 2].set(1).at[:, 3].set(jnp.arange(P, dtype=jnp.int32))

info = jnp.concatenate(
    [jax.random.randint(key, (4, N), 500, 100000, jnp.int32), jnp.ones((1, N), jnp.int32), jnp.zeros((3, N), jnp.int32)], 0
)
# Banded node matrix: rows 0:8 labels (others zero) -> dot(pod_f32, band) == sel @ labels
node_f32 = jnp.zeros((F, N), jnp.float32)
node_f32 = node_f32.at[0:8, :].set((jax.random.uniform(key, (8, N)) < 0.5).astype(jnp.float32))


def kern(req_ref, feat_ref, info_ref, nodef_ref, out_ref, best_ref, bestidx_ref):
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    tn = info_ref.shape[1]
    f32 = jnp.float32

    @pl.when(j == 0)
    def _():
        best_ref[:] = jnp.full_like(best_ref, float("-inf"))
        bestidx_ref[:] = jnp.zeros_like(bestidx_ref)

    avail = info_ref[0:2, :]
    alloc = info_ref[2:4, :]
    req_cpu = req_ref[:, 0:1]
    req_mem = req_ref[:, 1:2]
    act = req_ref[:, 2:3]
    ranks = req_ref[:, 3:4]
    fit = (req_cpu <= avail[0:1, :]) & (req_mem <= avail[1:2, :])
    counts = jnp.dot(feat_ref[:], nodef_ref[:], preferred_element_type=f32)
    selc = feat_ref[:, 40:41]
    sel_ok = counts == selc
    mask = fit & sel_ok & (act > 0)

    used_cpu = (alloc[0:1, :] - avail[0:1, :]) + req_cpu
    used_mem = (alloc[1:2, :] - avail[1:2, :]) + req_mem
    denom_cpu = jnp.maximum(alloc[0:1, :], 1).astype(f32)
    denom_mem = jnp.maximum(alloc[1:2, :], 1).astype(f32)
    frac_cpu = used_cpu.astype(f32) / denom_cpu
    frac_mem = used_mem.astype(f32) / denom_mem
    sc = ((f32(1.0) - frac_cpu) + (f32(1.0) - frac_mem)) * f32(50.0)
    sc = sc + (f32(1.0) - jnp.abs(frac_cpu - frac_mem)) * f32(100.0)
    u32 = jnp.uint32
    node_idx = (j * tn + jax.lax.broadcasted_iota(jnp.int32, (1, tn), 1)).astype(u32)
    h = ranks.astype(u32) * u32(2654435761) + node_idx * u32(2246822519)
    h = (h ^ (h >> u32(15))) & u32(0xFFFF)
    sc = sc + h.astype(jnp.int32).astype(f32) / f32(65536.0)
    sc = jnp.where(mask, sc, float("-inf"))

    tile_best = jnp.max(sc, axis=1, keepdims=True)
    tile_arg = jnp.argmax(sc, axis=1).reshape(-1, 1).astype(jnp.int32) + j * tn
    improve = tile_best > best_ref[:]
    bestidx_ref[:] = jnp.where(improve, tile_arg, bestidx_ref[:])
    best_ref[:] = jnp.where(improve, tile_best, best_ref[:])

    @pl.when(j == nb - 1)
    def _():
        out_ref[:] = bestidx_ref[:]


@jax.jit
def run(pod_i32, pod_f32, info, node_f32):
    return pl.pallas_call(
        kern,
        grid=(P // BP, N // TN),
        in_specs=[
            pl.BlockSpec((BP, 8), lambda i, j: (i, 0)),
            pl.BlockSpec((BP, F), lambda i, j: (i, 0)),
            pl.BlockSpec((8, TN), lambda i, j: (0, j)),
            pl.BlockSpec((F, TN), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((BP, 1), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((P, 1), jnp.int32),
        scratch_shapes=[pltpu.VMEM((BP, 1), jnp.float32), pltpu.VMEM((BP, 1), jnp.int32)],
    )(pod_i32, pod_f32, info, node_f32)


r = run(pod_i32, pod_f32, info, node_f32)
jax.block_until_ready(r)
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    jax.block_until_ready(run(pod_i32, pod_f32, info, node_f32))
    ts.append(time.perf_counter() - t0)
dt = min(ts)
print(f"wide-operand kernel (arguments): {dt*1e3:.1f} ms  ({P*N/dt/1e9:.2f} Gpair/s)")
