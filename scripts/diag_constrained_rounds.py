#!/usr/bin/env python
"""Fetch-fenced round attribution for the CONSTRAINED flagship cycle.

Times assign_cycle at the bench's constrained 100k x 10k shape for a ladder
of max_rounds values — the cumulative-time curve localizes where the 1.6 s
goes (big full-size rounds vs the long small-size tail).

Usage: python scripts/diag_constrained_rounds.py [pods] [nodes]
"""
import os
import sys
import time
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    from tpu_scheduler.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192)
    snap = synth_cluster(
        n_nodes=nodes, n_pending=pods, n_bound=2 * nodes, seed=0,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    s_pad, d_pad = cons.pod_sp_declares.shape[1], cons.node_dom_c.shape[1]
    t_pad = cons.pod_aa_carries.shape[1]
    print(
        f"padded {packed.padded_pods}x{packed.padded_nodes}; T={t_pad} S={s_pad} D={d_pad}"
        f"  t*d={t_pad*d_pad} s*d={s_pad*d_pad} (DENSE_CELLS gate: 1024)",
        flush=True,
    )

    backend = TpuBackend()
    prev = 0.0
    for mr in (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64):
        prof = profile.with_(max_rounds=mr)
        backend.schedule(packed, prof)  # compile/warm
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            r = backend.schedule(packed, prof)
            times.append(time.perf_counter() - t0)
        dt = min(times)
        print(
            f"max_rounds={mr:3d}: {dt:7.3f}s  (+{dt-prev:6.3f})  bound={len(r.bindings)}  rounds={r.rounds}",
            flush=True,
        )
        prev = dt


if __name__ == "__main__":
    main()
