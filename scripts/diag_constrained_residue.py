#!/usr/bin/env python
"""Round-5 diagnostic: the constrained flagship residue, classified.

BENCH_r04 showed the constrained 100k x 10k row stopping at the 64-round cap
with 81,768 bound — is the 18k residue genuinely infeasible (capacity /
constraint saturation) or cap-truncated?  This runs the bench's exact
constrained shape, prints the accepts-per-round histogram, re-runs at a much
higher cap, and replays the residue through the NATIVE sequential oracle to
count how many of the unbound pods any sequential scheduler could still
place.

Usage: python scripts/diag_constrained_residue.py [pods] [nodes] [seed]
"""
import os
import sys
import time
from collections import Counter
from dataclasses import replace

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def hist_str(acc_round):
    hist = Counter(int(x) for x in acc_round if x >= 0)
    return " ".join(f"{k}:{hist[k]}" for k in sorted(hist))


def main():
    pods = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    from tpu_scheduler.backends.native import NativeBackend
    from tpu_scheduler.backends.tpu import TpuBackend
    from tpu_scheduler.models.profiles import PROFILES
    from tpu_scheduler.ops.constraints import pack_constraints
    from tpu_scheduler.ops.pack import pack_snapshot
    from tpu_scheduler.testing import synth_cluster

    profile = PROFILES["throughput"].with_(pod_block=8192, max_rounds=64)
    snap = synth_cluster(
        n_nodes=nodes, n_pending=pods, n_bound=2 * nodes, seed=seed,
        anti_affinity_fraction=0.1, spread_fraction=0.1, schedule_anyway_fraction=0.1,
        pod_affinity_fraction=0.1, preferred_pod_affinity_fraction=0.1, extended_fraction=0.1,
    )
    packed = pack_snapshot(snap, pod_block=profile.pod_block, node_block=128)
    cons = pack_constraints(
        snap, snap.pending_pods(), packed.padded_pods, packed.node_names, packed.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    packed = replace(packed, constraints=cons)
    print(f"shape: {packed.num_pods}x{len(packed.node_names)} padded {packed.padded_pods}x{packed.padded_nodes}", flush=True)
    print(f"vocab: T={cons.n_terms} Ta={cons.n_pa_terms} Tp={cons.n_ppa_terms} S={cons.n_spread} Ss={cons.n_spread_soft}", flush=True)

    backend = TpuBackend()
    r = backend.schedule(packed, profile)  # warm/compile
    t0 = time.perf_counter()
    r = backend.schedule(packed, profile)
    dt = time.perf_counter() - t0
    print(f"cap=64: {dt:.3f}s bound={len(r.bindings)}/{packed.num_pods} rounds={r.rounds}", flush=True)
    print(f"  accepts/round: {hist_str(r.stats['acc_round'])}", flush=True)

    # Higher cap: does the auction keep finding placements past 64 rounds?
    prof256 = profile.with_(max_rounds=256)
    r256 = backend.schedule(packed, prof256)  # warm/compile
    t0 = time.perf_counter()
    r256 = backend.schedule(packed, prof256)
    dt256 = time.perf_counter() - t0
    print(f"cap=256: {dt256:.3f}s bound={len(r256.bindings)}/{packed.num_pods} rounds={r256.rounds}", flush=True)
    print(f"  accepts/round tail (>=60): {hist_str([x for x in r256.stats['acc_round'] if x >= 60])}", flush=True)

    # Residue oracle: rebuild a snapshot where the auction's placements are
    # BOUND, then ask the exact native sequential engine to place the
    # residue.  Anything it binds was cap/structure-truncated; the rest is
    # genuinely infeasible for any greedy sequential scheduler.
    import dataclasses

    from tpu_scheduler.api.objects import full_name
    from tpu_scheduler.core.snapshot import ClusterSnapshot

    bound_map = dict(r.bindings)
    print(f"residue after cap=64: {packed.num_pods - len(bound_map)} pods", flush=True)
    t0 = time.perf_counter()
    pods2 = [
        dataclasses.replace(p, spec=dataclasses.replace(p.spec, node_name=bound_map[full_name(p)]))
        if full_name(p) in bound_map and p.spec is not None and p.spec.node_name is None
        else p
        for p in snap.pods
    ]
    snap2 = ClusterSnapshot.build(snap.nodes, pods2)
    packed2 = pack_snapshot(snap2, pod_block=4096, node_block=128)
    cons2 = pack_constraints(
        snap2, snap2.pending_pods(), packed2.padded_pods, packed2.node_names, packed2.padded_nodes,
        max_aa_terms=256, max_spread=256,
    )
    if cons2 is not None:
        packed2 = replace(packed2, constraints=cons2)
    rn = NativeBackend().schedule(packed2, profile.with_(max_rounds=256))
    print(f"native oracle over residue: bound {len(rn.bindings)}/{packed2.num_pods} in {time.perf_counter()-t0:.1f}s", flush=True)
    print(f"=> genuinely infeasible: {packed2.num_pods - len(rn.bindings)}; cap/structure-truncated: {len(rn.bindings)}", flush=True)


if __name__ == "__main__":
    main()
