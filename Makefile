# Development gate — the discipline the reference enforces via
# .rustfmt.toml + .pre-commit-config.yaml (cargo check / clippy / fmt).
# `make check` is the pre-commit bar: nothing ships with it red.

PY ?= python

.PHONY: check lint analyze test native bench sim-smoke profile-smoke constrained-smoke delta-smoke defrag-smoke train-smoke latency-smoke elasticity-smoke protocol-smoke fuzz-smoke jit-stability-smoke clean

check: lint test profile-smoke constrained-smoke delta-smoke defrag-smoke train-smoke latency-smoke elasticity-smoke protocol-smoke fuzz-smoke jit-stability-smoke

lint: analyze
	$(PY) -m compileall -q tpu_scheduler tests scripts bench.py __graft_entry__.py

# The whole static-analysis policy (scripts/analyze/): ported hygiene rules
# plus THRD lock discipline, JAXP jit purity, DTRM sim determinism, SHPE
# shape contracts, EXCP failure-class closure, and the baseline gate (fails
# on new findings and on stale baseline entries).  The report artifact is
# consumed by bench.py provenance; --budget asserts the suite stays the
# fast part of this gate (pre-commit uses the --changed-only fast path).
analyze:
	$(PY) -m scripts.analyze --json-out .analyze_report.json --budget 5

test:
	$(PY) -m pytest tests/ -x -q

# The tier-1 simulation gate: one seeded scenario (~2k pods × 200 nodes,
# node churn + an api-brownout window) must finish green on CPU, plus the
# multi-replica failover scenario (two sharded replicas, owner crash-killed
# between solve and flush) — the same contracts tests/test_sim.py and
# tests/test_multi_replica_sim.py pin, runnable standalone for a verdict.
sim-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tpu_scheduler.cli sim --scenario sim-smoke --seed 0
	JAX_PLATFORMS=cpu $(PY) -m tpu_scheduler.cli sim --scenario replica-kill-mid-cycle --seed 0

# The profiler gate: one steady-state scenario with the always-on profiler,
# failing (exit 1) when attribution coverage drops below 0.9 or the measured
# span+ring overhead estimate exceeds 2% of the cycle wall — the same
# contracts tests/test_profiler.py pins, runnable standalone for a verdict.
profile-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tpu_scheduler.cli sim --scenario steady-state --seed 0 --profile-check

# The fused-conflict-filter gate: native-vs-jit binding parity on a
# constrained synth cluster plus a single-digit-seconds budget on the shape
# that needed ~60 s before the round-7 active-set fusion — fails (exit 1) if
# the filter ever re-grows a full-shape per-round sweep
# (scripts/constrained_smoke.py).
constrained-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.constrained_smoke

# The incremental-engine gate: the churn-steady-state scenario must pass
# with the scorecard incremental block green (delta cycles the default,
# zero shadow-solve parity mismatches) plus a delta-vs-full budget check on
# a downscaled synthetic cluster (scripts/delta_smoke.py).
delta-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.delta_smoke

# The background-rebalancer gate: the defrag-smoke fragmentation scenario
# must recover the scorecard rebalance block's packing-efficiency gate
# within its migration budget (zero orphaned migrations), while the
# rebalancer-off baseline must FAIL the same gate (scripts/defrag_smoke.py).
defrag-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.defrag_smoke

# The policy-learning gate: a tiny seeded CEM run (3 generations on the
# train-smoke scenario) must keep its best objective at or above the
# generation-0 default-profile objective, reproduce byte-identically from
# the one seed, and survive the tuned-artifact round-trip
# (scripts/train_smoke.py).
train-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.train_smoke

# The time-to-bind waterfall gate: the steady-state scenario must pass with
# the scorecard latency block green and segment coverage >= 0.95 of bound
# pods, and a live controller's /debug/latency route must serve the
# per-tier decomposition (scripts/latency_smoke.py).
latency-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.latency_smoke

# The closed-loop autoscaler gate: the flash-crowd elasticity scenario
# must pass its joint cost+SLO objective with real scale-ups and zero
# reclaim orphans, and the autoscaler-off static baseline must FAIL the
# same gate (scripts/elasticity_smoke.py).
elasticity-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.elasticity_smoke

# The protocol-verification gate: model-check every committed # protocol:
# spec against its crash/retry environment — all six protocol sites parse,
# zero invariant/progress violations, every composite state space within
# bounds, inside a pinned wall budget (scripts/protocol_smoke.py).
protocol-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.protocol_smoke

# The chaos-fuzzer gate: every checked-in reproducer in tests/fuzz_corpus/
# replays bit-identically, a pinned 24-plan seed-0 campaign finds zero
# violations, and coverage reaches its (fault-op × state-facet) floor —
# inside a pinned wall budget (scripts/fuzz_smoke.py).
fuzz-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.fuzz_smoke

# The compile-cache boundedness gate: the JITC/XFER analyzer rules must be
# clean over the annotated tree, and the steady-state scenario driven by
# the real TpuBackend (JAX on CPU) must show ZERO XLA compiles after the
# warmup window — the scorecard compile block live and flat
# (scripts/jit_stability_smoke.py).
jit-stability-smoke:
	JAX_PLATFORMS=cpu $(PY) -m scripts.jit_stability_smoke

# C++ shim (optional; ops/native_ext.py gates on its presence)
native:
	$(MAKE) -C native

# Regression-gated: fails (exit 2) when the flagship min-of-repeats exceeds
# the previous round's recorded number by >1.3x.  The driver's end-of-round
# run calls bench.py directly without the gate — a regressed number on
# record still beats none.
bench:
	$(PY) bench.py --fail-regression-threshold 1.3

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
	rm -rf build dist *.egg-info
